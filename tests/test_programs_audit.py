"""Program auditor (ISSUE 13): jaxpr-level contracts over the
registered program families.

Per contract family: one seeded-violation fixture proving the checker
FIRES with the right message (built from throwaway jitted programs, no
monkeypatching of the engine), plus the dogfood acceptance tests — the
full-repo audit is clean, the committed digest registry matches a fresh
trace, ``--update-digests`` is a byte-stable roundtrip, and the ``audit``
CLI exits 0 on this repo.
"""

import json
from pathlib import Path

import jax
import jax.numpy as jnp
import pytest

from heat_tpu.analysis.programs import (CONTRACTS, FAST_CONTRACTS,
                                        ProgramSpec, audit,
                                        check_compile_budget, check_digests,
                                        check_donation, check_dtype,
                                        check_purity, default_registry_path,
                                        donated_arg_indices,
                                        enumerate_step_keys,
                                        iter_program_specs, lane_static_prior,
                                        roofline_lane_step_bytes,
                                        trace_program)
from heat_tpu.cli import main

_KEY_DIMS = ("bucket", "lanes", "k", "kernel", "donate")


def _spec(name, fn, args, static=(), **kw):
    """A throwaway family over an ad-hoc jitted callable."""
    return ProgramSpec(name=name, build=lambda: (fn, args, tuple(static)),
                       **kw)


def _trace(spec):
    # fixtures must never pollute the process-wide trace cache
    return trace_program(spec, cache=False)


_F32_8 = jax.ShapeDtypeStruct((8,), jnp.float32)


# --- contract 1: donation ---------------------------------------------------

def test_donation_missing_alias_fires():
    spec = _spec("fixture/undonated", jax.jit(lambda x: x + 1.0),
                 (_F32_8,), donated=(0,))
    vs = check_donation(spec, _trace(spec))
    assert len(vs) == 1 and vs[0].rule == "program-donation"
    assert "declared donated" in vs[0].message
    assert "silently became a copy" in vs[0].message


def test_donation_rollback_must_not_alias():
    spec = _spec("fixture/rollback-aliased",
                 jax.jit(lambda x: x + 1.0, donate_argnums=(0,)),
                 (_F32_8,), no_alias=True)
    vs = check_donation(spec, _trace(spec))
    assert len(vs) == 1
    assert "must NOT alias" in vs[0].message


def test_donation_honored_is_clean():
    spec = _spec("fixture/donated",
                 jax.jit(lambda x: x + 1.0, donate_argnums=(0,)),
                 (_F32_8,), donated=(0,))
    assert check_donation(spec, _trace(spec)) == []


def test_donated_arg_indices_parses_main_signature():
    text = ("func.func public @main(%arg0: tensor<8xf32> "
            "{tf.aliasing_output = 0 : i32}, %arg1: tensor<8xf32>) "
            "-> (tensor<8xf32>) {")
    assert donated_arg_indices(text) == {0}
    assert donated_arg_indices("no main here") == set()


# --- contract 2: purity -----------------------------------------------------

def test_purity_seeded_callback_fires():
    def impure(x):
        y = jax.pure_callback(lambda a: a, _F32_8, x)
        return y + 1.0

    spec = _spec("fixture/impure", jax.jit(impure), (_F32_8,))
    vs = check_purity(spec, _trace(spec))
    assert len(vs) == 1 and vs[0].rule == "program-purity"
    assert "pure_callback" in vs[0].message
    assert "fences the dispatch pipeline" in vs[0].message


def test_purity_cold_program_unrestricted():
    def impure(x):
        return jax.pure_callback(lambda a: a, _F32_8, x)

    spec = _spec("fixture/cold-impure", jax.jit(impure), (_F32_8,),
                 hot=False)
    assert check_purity(spec, _trace(spec)) == []


# --- contract 3: dtype discipline -------------------------------------------

def test_dtype_silent_f64_promotion_fires():
    def widens(x):
        return (x.astype(jnp.float64) * 2.0).astype(jnp.float32)

    spec = _spec("fixture/f64-leak", jax.jit(widens), (_F32_8,))
    vs = check_dtype(spec, _trace(spec))
    assert len(vs) == 1 and vs[0].rule == "program-dtype"
    assert "silent f64 promotion" in vs[0].message


def test_dtype_bf16_without_storage_round_fires():
    spec = _spec("fixture/bf16-noround", jax.jit(lambda x: x * x),
                 (jax.ShapeDtypeStruct((8,), jnp.bfloat16),),
                 dtype="bfloat16", storage_round=True)
    vs = check_dtype(spec, _trace(spec))
    assert len(vs) == 1
    assert "round through storage" in vs[0].message


def test_dtype_clean_f32_passes():
    spec = _spec("fixture/f32-clean", jax.jit(lambda x: x * x), (_F32_8,))
    assert check_dtype(spec, _trace(spec)) == []


# --- contract 4: compile budget ---------------------------------------------

def test_budget_overflow_fires():
    reg = {"compile_budget": {"key_dims": list(_KEY_DIMS),
                              "max_programs": 3}}
    vs = check_compile_budget(reg, key_dims=_KEY_DIMS, enumerated=10)
    assert len(vs) == 1 and vs[0].rule == "compile-budget"
    assert "exceeds the declared budget (3)" in vs[0].message


def test_budget_new_key_dimension_fires():
    reg = {"compile_budget": {"key_dims": list(_KEY_DIMS),
                              "max_programs": 500}}
    vs = check_compile_budget(reg, key_dims=_KEY_DIMS + ("fuse",),
                              enumerated=1)
    assert len(vs) == 1
    assert "key dimensions changed" in vs[0].message


def test_budget_missing_declaration_fires():
    vs = check_compile_budget({}, key_dims=_KEY_DIMS, enumerated=1)
    assert len(vs) == 1
    assert "no declared compile budget" in vs[0].message


def test_enumeration_within_committed_budget():
    reg = json.loads(default_registry_path().read_text())
    enum = enumerate_step_keys()
    assert enum["total"] == enum["step_keys"] + enum["loaders"]
    assert enum["total"] <= reg["compile_budget"]["max_programs"]
    assert reg["compile_budget"]["key_dims"] == list(_KEY_DIMS)


# --- contract 5: digest drift -----------------------------------------------

def test_digest_drift_reports_op_delta():
    table = {"fam": {"digest": "b" * 16, "ops": {"add": 2, "mul": 1}}}
    reg = {"programs": {"fam": {"digest": "a" * 16,
                                "ops": {"add": 1, "sin": 1}}}}
    vs = check_digests(table, reg)
    assert len(vs) == 1 and vs[0].rule == "program-digest"
    msg = vs[0].message
    assert "digest drifted" in msg
    assert "added mul x1" in msg
    assert "removed sin x1" in msg
    assert "count add 1->2" in msg


def test_digest_same_ops_drift_names_operand_change():
    table = {"fam": {"digest": "b" * 16, "ops": {"add": 1}}}
    reg = {"programs": {"fam": {"digest": "a" * 16, "ops": {"add": 1}}}}
    (v,) = check_digests(table, reg)
    assert "identical op histogram" in v.message


def test_digest_new_and_removed_families_fire():
    table = {"new": {"digest": "b" * 16, "ops": {}}}
    reg = {"programs": {"old": {"digest": "a" * 16, "ops": {}}}}
    msgs = sorted(v.message for v in check_digests(table, reg))
    assert len(msgs) == 2
    assert any("new program family 'new'" in m for m in msgs)
    assert any("no longer registered" in m for m in msgs)


def test_digest_registry_missing_fires():
    (v,) = check_digests({}, None)
    assert "registry missing" in v.message


def test_perturbed_registry_drifts_end_to_end(tmp_path):
    reg = json.loads(default_registry_path().read_text())
    name = sorted(reg["programs"])[0]
    reg["programs"][name]["digest"] = "0" * 16
    p = tmp_path / "programs.json"
    p.write_text(json.dumps(reg))
    vs, report = audit(registry_path=p, contracts=("program-digest",))
    assert report["digest_gate"] == "checked"
    assert [v for v in vs if f"drifted for {name!r}" in v.message]


# --- the static prior -------------------------------------------------------

def test_roofline_prior_parses_bucket_labels():
    assert roofline_lane_step_bytes(2, 256, "float32") == 2 * 258**2 * 4
    prior = lane_static_prior("2d/n256/float32/edges")
    assert prior and prior > 0
    assert lane_static_prior("not-a-bucket") is None
    # bf16 moves half the bytes of f32 at the same geometry
    assert (lane_static_prior("2d/n256/bfloat16/edges")
            == pytest.approx(prior / 2))


def test_registry_exports_static_cost():
    reg = json.loads(default_registry_path().read_text())
    lanes = {k: v for k, v in reg["programs"].items()
             if v.get("bucket")}
    assert lanes, "lane families must export their cost-model bucket"
    for ent in lanes.values():
        assert ent["roofline_bytes_per_lane_step"] > 0


# --- acceptance: the repo audits clean --------------------------------------

def test_full_repo_audit_is_clean():
    vs, report = audit()
    assert vs == []
    assert report["families"] == report["traced"] == len(iter_program_specs())
    assert report["digest_gate"] == "checked"
    assert (report["budget"]["declared"]
            == report["budget"]["enumerated"]["total"])
    assert set(FAST_CONTRACTS) < set(CONTRACTS)


def test_update_digests_roundtrip_is_byte_stable(tmp_path):
    p = tmp_path / "programs.json"
    vs, report = audit(registry_path=p, update_digests=True)
    assert vs == [] and report["digest_gate"] == "updated"
    first = p.read_text()
    audit(registry_path=p, update_digests=True)
    assert p.read_text() == first
    # and a fresh trace matches what this checkout has committed
    fresh = json.loads(first)["programs"]
    committed = json.loads(default_registry_path().read_text())["programs"]
    assert ({k: v["digest"] for k, v in fresh.items()}
            == {k: v["digest"] for k, v in committed.items()})
    vs, report = audit(registry_path=p)
    assert vs == [] and report["digest_gate"] == "checked"


def test_audit_rejects_unknown_contract():
    with pytest.raises(ValueError, match="unknown contract"):
        audit(contracts=("no-such-contract",))


def test_jax_version_skew_skips_digest_gate(tmp_path):
    reg = json.loads(default_registry_path().read_text())
    reg["jax"] = "0.0.0-other"
    for ent in reg["programs"].values():
        ent["digest"] = "f" * 16   # would all drift if the gate ran
    p = tmp_path / "programs.json"
    p.write_text(json.dumps(reg))
    vs, report = audit(registry_path=p, contracts=("program-digest",))
    assert vs == []
    assert report["digest_gate"].startswith("skipped")


# --- the CLI ----------------------------------------------------------------

def test_audit_cli_end_to_end(capsys):
    assert main(["audit"]) == 0
    out = capsys.readouterr().out
    assert "heat-tpu audit: OK" in out
    assert "digest gate checked" in out


def test_audit_cli_fast_and_json(capsys):
    assert main(["audit", "--fast", "--json"]) == 0
    rep = json.loads(capsys.readouterr().out)
    assert sorted(rep["contracts"]) == sorted(FAST_CONTRACTS)
    assert rep["violations"] == 0 and rep["violation_list"] == []


def test_audit_cli_usage_errors(capsys):
    assert main(["audit", "--contracts", "nope"]) == 2
    assert main(["audit", "--fast", "--contracts", "program-digest"]) == 2
    capsys.readouterr()
    assert main(["audit", "--list-contracts"]) == 0
    out = capsys.readouterr().out
    for cid in CONTRACTS:
        assert cid in out


def test_audit_cli_fails_on_drifted_registry(tmp_path, capsys):
    reg = json.loads(default_registry_path().read_text())
    name = sorted(reg["programs"])[0]
    reg["programs"][name]["digest"] = "0" * 16
    p = tmp_path / "programs.json"
    p.write_text(json.dumps(reg))
    assert main(["audit", "--registry", str(p),
                 "--contracts", "program-digest"]) == 1
    out = capsys.readouterr().out
    assert "digest drifted" in out
    assert "heat-tpu audit: FAILED" in out


def test_info_reports_program_audit_line(capsys):
    assert main(["info"]) == 0
    out = capsys.readouterr().out
    assert "program audit:" in out
    assert "compile budget declared=" in out


# --- registry seams ---------------------------------------------------------

def test_program_specs_cover_every_family_axis():
    specs = iter_program_specs()
    names = [s.name for s in specs]
    assert len(names) == len(set(names)), "family names must be unique"
    fams = {s.family for s in specs}
    assert {"solo", "lane", "loader", "mega"} <= fams
    assert any(s.no_alias for s in specs), "rollback family registered"
    assert any(s.storage_round for s in specs), "bf16 family registered"
    assert any(s.dtype == "float64" for s in specs)
    assert any(s.kernel == "pallas" for s in specs)
