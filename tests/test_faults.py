"""Fault-injection layer (runtime/faults.py) + the recovery semantics it
exists to exercise: --on-nan rollback, checkpoint quarantine + fallback,
transient-sink retry. Single-process and fast — the multi-process
supervisor e2e lives in test_chaos.py (slow-marked)."""

import pathlib

import numpy as np
import pytest

from heat_tpu.backends import solve
from heat_tpu.config import HeatConfig
from heat_tpu.runtime import checkpoint, faults


@pytest.fixture(autouse=True)
def _fresh_plans():
    """Firing state is cached per spec string per process; tests must not
    inherit a previous test's spent faults."""
    faults.reset()
    yield
    faults.reset()


# --- spec grammar -----------------------------------------------------------


def test_parse_spec_full_grammar():
    fs = faults.parse_spec(
        "crash@8:proc=1,nan@6,ckpt-corrupt@4,ckpt-truncate@4,"
        "sink-error@2:times=3,sink-slow:ms=7:restart=-1")
    kinds = [f.kind for f in fs]
    assert kinds == ["crash", "nan", "ckpt-corrupt", "ckpt-truncate",
                     "sink-error", "sink-slow"]
    assert fs[0].step == 8 and fs[0].proc == 1
    assert fs[4].times == 3
    assert fs[5].ms == 7.0 and fs[5].restart == -1


@pytest.mark.parametrize("bad", [
    "bogus@3",            # unknown kind
    "crash",              # crash needs a step
    "nan@x",              # non-integer step
    "crash@3:zorp=1",     # unknown key
    "sink-error@2:times=abc",
])
def test_parse_spec_rejects_typos(bad):
    with pytest.raises(ValueError):
        faults.parse_spec(bad)


def test_config_validates_inject_spec_at_parse_time():
    with pytest.raises(ValueError, match="unknown fault kind"):
        HeatConfig(inject="bogus@3")
    HeatConfig(inject="nan@6")  # valid spec constructs fine


def test_parse_spec_serve_kinds_grammar():
    """The serve-scoped kinds (ISSUE 5): lane-nan needs a step and takes
    an optional req= target; fetch-hang takes ms= and an optional @N
    fetch index."""
    fs = faults.parse_spec("lane-nan@5:req=abc,lane-nan@9,"
                           "fetch-hang@2:ms=250,fetch-hang:ms=10")
    assert [f.kind for f in fs] == ["lane-nan", "lane-nan",
                                    "fetch-hang", "fetch-hang"]
    assert fs[0].step == 5 and fs[0].req == "abc"
    assert fs[1].step == 9 and fs[1].req is None
    assert fs[2].step == 2 and fs[2].ms == 250.0
    assert fs[3].step is None and fs[3].ms == 10.0
    with pytest.raises(ValueError, match="needs a step"):
        faults.parse_spec("lane-nan:req=a")


def test_lane_nan_steps_filter_by_request_id():
    """req=-targeted faults apply only to that id; untargeted ones apply
    to every request. Firing state lives in the scheduler (per request),
    so the plan only answers thresholds."""
    plan = faults.FaultPlan("lane-nan@5,lane-nan@9:req=b")
    assert plan.lane_nan_steps("a") == [5]
    assert plan.lane_nan_steps("b") == [5, 9]
    # asking twice must not consume anything
    assert plan.lane_nan_steps("b") == [5, 9]


def test_parse_spec_perturb_grammar():
    """The numerics-observatory fault (ISSUE 15): perturb needs a step,
    takes optional req= targeting and an eps= magnitude (default 1e3 —
    finite, far past any envelope tolerance)."""
    fs = faults.parse_spec("perturb@16:req=a:eps=2.5,perturb@8")
    assert [f.kind for f in fs] == ["perturb", "perturb"]
    assert fs[0].step == 16 and fs[0].req == "a" and fs[0].eps == 2.5
    assert fs[1].step == 8 and fs[1].req is None and fs[1].eps == 1e3
    with pytest.raises(ValueError, match="needs a step"):
        faults.parse_spec("perturb:eps=5")
    with pytest.raises(ValueError, match="bad fault param"):
        faults.parse_spec("perturb@4:zorp=1")
    with pytest.raises(ValueError):
        faults.parse_spec("perturb@4:eps=abc")


def test_perturb_events_filter_by_request_id():
    """Same per-request contract as lane_nan_steps: req=-targeted events
    apply only to that id, untargeted to every request, and asking never
    consumes firing state (that lives in the scheduler)."""
    plan = faults.FaultPlan("perturb@8,perturb@16:req=b:eps=2.5")
    assert plan.perturb_events("a") == [(8, 1e3)]
    assert plan.perturb_events("b") == [(8, 1e3), (16, 2.5)]
    # asking twice must not consume anything
    assert plan.perturb_events("b") == [(8, 1e3), (16, 2.5)]
    assert faults.plan_for(HeatConfig(inject="perturb@4")) is not None


def test_fetch_hang_fires_once_at_threshold():
    plan = faults.FaultPlan("fetch-hang@2:ms=1")
    plan.maybe_fetch_hang(0)
    plan.maybe_fetch_hang(1)
    assert not plan.faults[0].fired       # below the @2 threshold
    plan.maybe_fetch_hang(2)
    assert plan.faults[0].fired           # fired (and slept 1 ms)


def test_plan_for_none_hot_path_including_serve_kinds(monkeypatch):
    """Satellite (ISSUE 5): with no --inject/HEAT_TPU_FAULTS the fault
    layer stays entirely out of the hot path — plan_for is None for solo
    AND serve configs, and a serve Engine built without a spec carries no
    plan and no lane-fault gate."""
    monkeypatch.delenv(faults.ENV_VAR, raising=False)
    from heat_tpu.serve import Engine, ServeConfig

    assert faults.plan_for(HeatConfig()) is None
    assert faults.plan_for(ServeConfig()) is None
    eng = Engine(ServeConfig(emit_records=False))
    assert eng._plan is None and eng._has_lane_faults is False
    # serve-scoped kinds are opt-in exactly like the others
    assert faults.plan_for(
        ServeConfig(inject="lane-nan@3")) is not None
    assert faults.plan_for(
        HeatConfig(inject="fetch-hang:ms=5")) is not None
    # and env-channel specs with serve kinds activate without cfg plumbing
    monkeypatch.setenv(faults.ENV_VAR, "lane-nan@3")
    assert faults.plan_for(ServeConfig()) is not None


def test_plan_for_is_strictly_opt_in(monkeypatch):
    monkeypatch.delenv(faults.ENV_VAR, raising=False)
    assert faults.plan_for(HeatConfig()) is None
    assert faults.plan_for(None) is None
    # env var is the worker-process channel
    monkeypatch.setenv(faults.ENV_VAR, "nan@6")
    plan = faults.plan_for(HeatConfig())
    assert plan is not None and plan.faults[0].kind == "nan"
    # cfg.inject wins over the env var
    assert faults.plan_for(HeatConfig(inject="crash@2")).faults[0].kind == "crash"


def test_restart_gate_suppresses_fired_faults(monkeypatch):
    """A restarted incarnation (HEAT_TPU_RESTART=1) must not re-fire a
    default (restart=0) fault — the self-healing relaunch would otherwise
    die the same death forever."""
    monkeypatch.setenv(faults.RESTART_ENV_VAR, "1")
    cfg = HeatConfig(n=16, ntime=8, dtype="float64", backend="xla",
                     inject="nan@4", check_numerics=True)
    res = solve(cfg)  # nan@4 is gated off: completes clean
    assert np.isfinite(res.T).all()
    # restart=-1 fires in every incarnation
    faults.reset()
    with pytest.raises(FloatingPointError):
        solve(cfg.with_(inject="nan@4:restart=-1"))


def test_parse_spec_fleet_resilience_kinds_grammar():
    """The fleet chaos kinds (ISSUE 20): backend-flap needs period=,
    stream-cut needs a step, backend-partition takes optional ms= and
    backend= and nothing else."""
    fs = faults.parse_spec(
        "backend-flap:period=500:backend=b1:times=2,"
        "stream-cut@3:backend=b0,stream-cut@7,"
        "backend-partition:ms=50:backend=b2,backend-partition")
    assert [f.kind for f in fs] == ["backend-flap", "stream-cut",
                                    "stream-cut", "backend-partition",
                                    "backend-partition"]
    assert fs[0].period == 500.0 and fs[0].backend == "b1"
    assert fs[0].times == 2
    assert fs[1].step == 3 and fs[1].backend == "b0"
    assert fs[2].step == 7 and fs[2].backend is None
    assert fs[3].ms == 50.0 and fs[3].backend == "b2"
    assert fs[4].ms == 0.0 and fs[4].backend is None
    with pytest.raises(ValueError, match="needs a half-period"):
        faults.parse_spec("backend-flap")
    with pytest.raises(ValueError, match="needs a half-period"):
        faults.parse_spec("backend-flap:period=0")
    with pytest.raises(ValueError, match="needs a step"):
        faults.parse_spec("stream-cut:backend=b0")
    with pytest.raises(ValueError):
        faults.parse_spec("backend-partition:zorp=1")


def test_backend_flap_states_square_wave():
    """The flap schedule is a pure function of (epoch, period, times):
    down on even phases, up between, up forever after the last down
    pulse. The epoch stamps on first evaluation."""
    plan = faults.FaultPlan("backend-flap:period=100:backend=b1:times=2")
    t0 = 1000.0
    assert plan.backend_flap_states(t0) == {"b1": True}     # phase 0
    assert plan.backend_flap_states(t0 + 0.15) == {"b1": False}  # phase 1
    assert plan.backend_flap_states(t0 + 0.25) == {"b1": True}   # phase 2
    # after 2 down pulses (phase >= 3): up forever
    assert plan.backend_flap_states(t0 + 0.35) == {"b1": False}
    assert plan.backend_flap_states(t0 + 99.0) == {"b1": False}
    # default target is b0; default times=1 = single pulse
    p2 = faults.FaultPlan("backend-flap:period=50")
    assert p2.backend_flap_states(5.0) == {"b0": True}
    assert p2.backend_flap_states(5.0 + 0.06) == {"b0": False}


def test_stream_cut_fires_once_per_matching_backend():
    plan = faults.FaultPlan("stream-cut@2:backend=b1")
    assert not plan.stream_cut_fire("b0", 5)   # wrong target
    assert not plan.stream_cut_fire("b1", 1)   # below threshold
    assert plan.stream_cut_fire("b1", 2)       # fires
    assert not plan.stream_cut_fire("b1", 3)   # spent (fire-once)
    # untargeted: the first relay to reach the threshold takes it
    p2 = faults.FaultPlan("stream-cut@1")
    assert p2.stream_cut_fire("bX", 1)
    assert not p2.stream_cut_fire("bY", 9)


def test_backend_partition_persists_and_defaults():
    plan = faults.FaultPlan("backend-partition:backend=b2:ms=25")
    assert plan.backend_partition_ms("b2") == 25.0
    assert plan.backend_partition_ms("b2") == 25.0   # NOT fire-once
    assert plan.backend_partition_ms("b0") is None
    # untargeted partition hits every backend; ms defaults to 1000
    p2 = faults.FaultPlan("backend-partition")
    assert p2.backend_partition_ms("anything") == 1000.0


def test_fleet_kinds_stay_out_of_hot_path(monkeypatch):
    """No spec -> no plan: the three fleet kinds are strictly opt-in
    like every other fault (the router carries only plan-is-None
    tests)."""
    monkeypatch.delenv(faults.ENV_VAR, raising=False)
    assert faults.plan_for_spec("") is None
    plan = faults.plan_for_spec("backend-flap:period=100")
    assert plan is not None
    assert plan.backend_partition_ms("b0") is None
    assert not plan.stream_cut_fire("b0", 100)


# --- nan injection + --on-nan ----------------------------------------------


def test_injected_nan_aborts_by_default():
    # heartbeat_every=2 pins the chunk (event_interval) to 2, so the fault
    # fires exactly at its nominal step and the error names it
    cfg = HeatConfig(n=16, ntime=8, dtype="float64", backend="xla",
                     check_numerics=True, heartbeat_every=2, inject="nan@4")
    with pytest.raises(FloatingPointError, match="step 4"):
        solve(cfg)


def test_on_nan_rollback_requires_check_numerics():
    with pytest.raises(ValueError, match="rollback"):
        HeatConfig(on_nan="rollback")
    with pytest.raises(ValueError, match="on_nan"):
        HeatConfig(on_nan="retry", check_numerics=True)


@pytest.mark.parametrize("async_io", ["auto", "off"])
def test_on_nan_rollback_recovers_transient_nan(tmp_path, async_io):
    """The headline recovery semantic: a transient non-finite boundary
    (injected NaN) rolls back to the last verified-finite boundary,
    re-steps, and finishes with a final field BIT-IDENTICAL to an
    uninterrupted run — on both I/O paths."""
    cfg = HeatConfig(n=24, ntime=12, dtype="float64", backend="xla",
                     check_numerics=True, on_nan="rollback",
                     checkpoint_every=2, async_io=async_io,
                     checkpoint_dir=str(tmp_path / "ck"), inject="nan@6")
    res = solve(cfg)
    clean = solve(HeatConfig(n=24, ntime=12, dtype="float64", backend="xla"))
    np.testing.assert_array_equal(res.T, clean.T)
    # every checkpoint on disk is finite (the NaN boundary never persisted)
    names = sorted(p.name for p in (tmp_path / "ck").glob("*.npz"))
    assert names == [f"heat_step{s:08d}.npz" for s in range(2, 13, 2)]
    for name in names:
        T, _ = checkpoint.load(tmp_path / "ck" / name,
                               cfg.with_(inject="", on_nan="abort",
                                         check_numerics=False))
        assert np.isfinite(T).all()


def test_on_nan_rollback_aborts_deterministic_blowup():
    """sigma far past the FTCS bound re-flags the same step after every
    rollback: the bounded retry budget must declare it deterministic and
    abort instead of looping forever."""
    cfg = HeatConfig(n=16, ntime=400, sigma=2.0, dtype="float32",
                     backend="xla", check_numerics=True, on_nan="rollback",
                     heartbeat_every=10)
    with pytest.raises(FloatingPointError):
        solve(cfg)


def test_rollback_serial_backend_still_aborts():
    """The rollback driver lives in backends/common.drive (device
    backends); the serial oracle keeps the abort contract."""
    cfg = HeatConfig(n=16, ntime=8, dtype="float64", backend="serial",
                     check_numerics=True, on_nan="rollback", inject="nan@4")
    with pytest.raises(FloatingPointError):
        solve(cfg)


# --- checkpoint damage + quarantine ----------------------------------------


@pytest.mark.parametrize("kind", ["ckpt-corrupt", "ckpt-truncate"])
def test_damaged_newest_checkpoint_quarantined_and_fallback(tmp_path, kind):
    """Acceptance criterion: corrupting the newest checkpoint makes resume
    fall back to the next-older step, with the bad file renamed to
    ``*.corrupt``."""
    d = tmp_path / "ck"
    cfg = HeatConfig(n=24, ntime=6, dtype="float64", backend="xla",
                     checkpoint_every=2, checkpoint_dir=str(d),
                     inject=f"{kind}@6")
    solve(cfg)  # steps 2, 4 intact; step 6 damaged post-publish
    res = solve(cfg.with_(ntime=10, inject=""))
    assert res.start_step == 4
    assert sorted(p.name for p in d.glob("*.corrupt")) == [
        "heat_step00000006.npz.corrupt"]
    clean = solve(HeatConfig(n=24, ntime=10, dtype="float64", backend="xla"))
    np.testing.assert_array_equal(res.T, clean.T)


def test_shard_checkpoint_quarantine_falls_back(tmp_path, monkeypatch):
    """latest_shards applies the same validate->quarantine->older-step
    contract to per-process shard files."""
    import heat_tpu.backends.common as common

    monkeypatch.setattr(common, "_addressable", lambda x: False)
    d = tmp_path / "ck"
    cfg = HeatConfig(n=16, ntime=4, dtype="float32", backend="sharded",
                     mesh_shape=(2, 2), checkpoint_every=2,
                     checkpoint_dir=str(d))
    solve(cfg)  # shard files at steps 2 and 4
    newest = d / "heat_shards_step00000004.proc0000.npz"
    newest.write_bytes(newest.read_bytes()[:100])  # torn write
    res = solve(cfg.with_(ntime=6))
    assert res.start_step == 2
    assert (d / "heat_shards_step00000004.proc0000.npz.corrupt").exists()


def test_non_finite_checkpoint_is_quarantined(tmp_path):
    """A checkpoint that loads but carries NaN is as dead as a torn one."""
    d = tmp_path / "ck"
    cfg = HeatConfig(n=16, ntime=4, dtype="float64", backend="xla",
                     checkpoint_every=2, checkpoint_dir=str(d))
    solve(cfg)
    # overwrite the newest with a NaN field under a valid fingerprint
    bad = np.full((16, 16), np.nan)
    checkpoint.save(cfg, bad, 4)
    assert checkpoint.latest_step(cfg) == 2
    assert (d / "heat_step00000004.npz.corrupt").exists()


def test_scan_resume_step_supervisor_view(tmp_path):
    """The launch supervisor's config-free discovery: singles + complete
    shard sets count, partial shard sets don't, corrupt candidates are
    quarantined during the scan."""
    d = tmp_path / "ck"
    cfg = HeatConfig(n=16, ntime=4, dtype="float64", backend="xla",
                     checkpoint_every=2, checkpoint_dir=str(d))
    solve(cfg)  # heat_step...2/4.npz
    assert checkpoint.scan_resume_step(d) == 4
    assert checkpoint.scan_resume_step(d, max_step=3) == 2
    # a fake 2-proc shard set: complete at step 6, partial at step 8
    for proc in (0, 1):
        src = (d / "heat_step00000004.npz").read_bytes()
        (d / f"heat_shards_step00000006.proc{proc:04d}.npz").write_bytes(src)
    (d / "heat_shards_step00000008.proc0000.npz").write_bytes(src)
    assert checkpoint.scan_resume_step(d, nprocs=2) == 6
    # corrupt the newest single: quarantined mid-scan, falls back
    p4 = d / "heat_step00000004.npz"
    p4.write_bytes(p4.read_bytes()[:80])
    assert checkpoint.scan_resume_step(d, nprocs=2) == 6
    assert (d / "heat_step00000004.npz.corrupt").exists()
    assert checkpoint.scan_resume_step(tmp_path / "nope") is None


# --- transient sink faults through the async writer -------------------------


def test_transient_sink_error_absorbed_by_writer_retry(tmp_path):
    """Two injected EIO write failures stay below the writer's retry
    budget: the solve completes and every checkpoint lands."""
    d = tmp_path / "ck"
    cfg = HeatConfig(n=16, ntime=6, dtype="float64", backend="xla",
                     checkpoint_every=2, checkpoint_dir=str(d),
                     inject="sink-error@2:times=2")
    solve(cfg)
    assert sorted(p.name for p in d.glob("*.npz")) == [
        f"heat_step{s:08d}.npz" for s in (2, 4, 6)]


def test_persistent_sink_error_surfaces(tmp_path):
    cfg = HeatConfig(n=16, ntime=6, dtype="float64", backend="xla",
                     checkpoint_every=2,
                     checkpoint_dir=str(tmp_path / "ck"),
                     inject="sink-error@2:times=99")
    with pytest.raises(OSError, match="injected transient sink error"):
        solve(cfg)


def test_slow_sink_fault_only_delays(tmp_path):
    cfg = HeatConfig(n=16, ntime=4, dtype="float64", backend="xla",
                     checkpoint_every=2, checkpoint_dir=str(tmp_path / "ck"),
                     inject="sink-slow:ms=20")
    res = solve(cfg)
    assert np.isfinite(res.T).all()
    assert len(list((tmp_path / "ck").glob("*.npz"))) == 2


# --- crash fault (subprocess: os._exit must not kill the test runner) -------


def test_crash_fault_exits_with_chaos_rc(tmp_cwd):
    import os
    import subprocess
    import sys
    from pathlib import Path

    env = {**os.environ,
           "PYTHONPATH": str(Path(__file__).resolve().parent.parent)
           + os.pathsep + os.environ.get("PYTHONPATH", "")}
    (tmp_cwd / "input.dat").write_text("16 0.25 0.05 2.0 6 0\n")
    code = (
        "import jax\n"
        "jax.config.update('jax_platforms', 'cpu')\n"
        "from heat_tpu.cli import main\n"
        "raise SystemExit(main(['run', '--backend', 'serial',\n"
        "                       '--dtype', 'float64',\n"
        "                       '--inject', 'crash@3']))\n"
    )
    p = subprocess.run([sys.executable, "-c", code], cwd=tmp_cwd, env=env,
                       capture_output=True, text=True, timeout=120)
    assert p.returncode == faults.CRASH_RC, p.stderr
    assert "injected crash at step 3" in p.stderr


def test_no_inject_leaves_solve_bit_identical(tmp_path, monkeypatch):
    """Strict opt-in: inject='' must not perturb results or checkpoint
    bytes (the hot path carries only a plan-is-None test)."""
    monkeypatch.delenv(faults.ENV_VAR, raising=False)
    da, db = tmp_path / "a", tmp_path / "b"
    base = HeatConfig(n=24, ntime=8, dtype="float64", backend="xla",
                      checkpoint_every=4)
    ra = solve(base.with_(checkpoint_dir=str(da)))
    rb = solve(base.with_(checkpoint_dir=str(db), inject=""))
    np.testing.assert_array_equal(ra.T, rb.T)
    for name in ("heat_step00000004.npz", "heat_step00000008.npz"):
        assert (da / name).read_bytes() == (db / name).read_bytes()
