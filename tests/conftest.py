"""Test harness: 8 virtual CPU devices + f64 enabled.

Mirrors the reference's develop-without-a-cluster story (`mpirun -np N` on
one node, fortran/mpi+cuda/makefile:1-2): halo-exchange and sharding logic
run on a fake 8-device CPU mesh; the real chip is only needed for perf.
"""

import os

# Must land before the first backend initialization.
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
)

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def tmp_cwd(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    return tmp_path


@pytest.fixture(autouse=True)
def _seed():
    np.random.seed(0)
