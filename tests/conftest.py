"""Test harness: 8 virtual CPU devices + f64 enabled.

Mirrors the reference's develop-without-a-cluster story (`mpirun -np N` on
one node, fortran/mpi+cuda/makefile:1-2): halo-exchange and sharding logic
run on a fake 8-device CPU mesh; the real chip is only needed for perf.
"""

import os

# Must land before the first backend initialization.
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
)

# The suite is CPU-pinned (chips are for perf, not tests) but the
# chipless topology tests still construct against libtpu, and on a host
# without a reachable GCP metadata server each construct burns ~30 HTTP
# retries per metadata variable — one test then stalls for minutes at
# ~0% CPU (the tunnel-wedge signature) and eats the tier-1 wall budget.
# Topology descriptors don't need instance metadata; skip the queries.
os.environ.setdefault("TPU_SKIP_MDS_QUERY", "1")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

import threading  # noqa: E402

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def tmp_cwd(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    return tmp_path


@pytest.fixture(autouse=True)
def _seed():
    np.random.seed(0)


# Thread names the serving stack spawns; a test that exits while one is
# still alive forgot shutdown()/drain and would leak its scheduler into
# every later test (flaky cross-test interference, wedged CI teardown).
_SERVE_THREAD_PREFIXES = ("heat-tpu-serve-scheduler", "heat-snapshot-writer",
                          "heat-tpu-gateway", "heat-tpu-prober")


@pytest.fixture(autouse=True)
def _no_leaked_serve_threads():
    """Fail the test that leaked a serving thread, not a random later one.

    Checks by name so unrelated daemon helpers (e.g. the bounded-fetch
    pool, which is process-lifetime by design) never false-positive.
    Gives stragglers a short grace join first — a drained scheduler can
    still be inside its last few bookkeeping lines when wait() returns.
    """
    yield
    leaked = []
    for t in threading.enumerate():
        if t is threading.current_thread() or not t.is_alive():
            continue
        if t.name.startswith(_SERVE_THREAD_PREFIXES):
            t.join(timeout=5.0)
            if t.is_alive():
                leaked.append(t.name)
    assert not leaked, (
        f"test leaked live serving thread(s): {leaked} — call "
        f"Engine.shutdown() / Gateway.request_drain()+close() before "
        f"returning")


# The repo root once accumulated 81 stray flightrec-*.trace.json dumps
# from tests whose engines had neither flight_dir nor out_dir set (the
# dump used to default to cwd). The dump now lands in out_dir or is
# skipped; this guard keeps the litter from ever coming back.
_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _no_flightrec_litter():
    """Fail any test that leaks a flight-recorder dump (or engine
    checkpoint dir) outside its tmp_path into the repo root."""
    import glob

    def _strays():
        return sorted(
            glob.glob(os.path.join(_REPO_ROOT, "flightrec-*.trace.json"))
            + glob.glob(os.path.join(_REPO_ROOT, "engine-ckpt", "*")))

    before = set(_strays())
    yield
    leaked = [p for p in _strays() if p not in before]
    for p in leaked:
        os.unlink(p)   # clean up so ONE offender doesn't fail the rest
    assert not leaked, (
        f"test leaked dump(s) into the repo root: "
        f"{[os.path.basename(p) for p in leaked]} — pass flight_dir/"
        f"out_dir/engine_ckpt_dir pointing at tmp_path")
