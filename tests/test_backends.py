"""Cross-backend oracle equivalence — every device backend must reproduce the
numpy oracle (the cross-variant consistency the reference never automated,
SURVEY.md §4)."""

import numpy as np
import pytest

from heat_tpu.backends import get_backend, solve
from heat_tpu.config import HeatConfig


BASE = HeatConfig(n=32, ntime=20, dtype="float64", backend="serial")


def _oracle(cfg):
    return solve(cfg.with_(backend="serial"))


@pytest.mark.parametrize("backend", ["xla", "pallas"])
@pytest.mark.parametrize("bc,ic", [("edges", "hat"), ("ghost", "uniform")])
def test_backend_matches_oracle_f64(backend, bc, ic):
    cfg = BASE.with_(bc=bc, ic=ic)
    expect = _oracle(cfg)
    got = solve(cfg.with_(backend=backend))
    np.testing.assert_allclose(got.T, expect.T, rtol=0, atol=0)


@pytest.mark.parametrize("backend", ["xla", "pallas"])
def test_backend_f32(backend):
    cfg = BASE.with_(dtype="float32", ntime=30)
    expect = _oracle(cfg)
    got = solve(cfg.with_(backend=backend))
    np.testing.assert_allclose(got.T, expect.T, rtol=0, atol=5e-6)


def test_bf16_storage_f32_accumulate():
    """bf16 runs stay stable and land near the f32 answer."""
    cfg = BASE.with_(dtype="bfloat16", n=64, ntime=25)
    ref = solve(cfg.with_(backend="serial", dtype="float32"))
    got = solve(cfg.with_(backend="xla"))
    assert got.T.dtype == np.float32 or got.T.dtype.name == "bfloat16"
    np.testing.assert_allclose(
        np.asarray(got.T, np.float32), ref.T, rtol=0, atol=3e-2
    )


@pytest.mark.parametrize("backend", ["xla", "pallas"])
def test_3d_backends(backend):
    cfg = HeatConfig(n=16, ndim=3, ntime=6, dtype="float64", ic="hat",
                     sigma=0.15)
    expect = _oracle(cfg)
    got = solve(cfg.with_(backend=backend))
    # XLA may reassociate the 7-point sum: allow ~1 ulp
    np.testing.assert_allclose(got.T, expect.T, rtol=0, atol=1e-14)


def test_pallas_availability():
    from heat_tpu.ops.pallas_stencil import pallas_available

    assert pallas_available((256, 256), np.float32)
    assert pallas_available((100, 100), np.float32)      # internal padding
    assert pallas_available((130, 130), np.float32)      # ghost-padded sizes
    assert pallas_available((256, 128, 128), np.float32)
    assert pallas_available((100, 100, 100), np.float32)  # 3D padding too
    assert not pallas_available((256, 256), np.float64)  # no f64 on TPU VPU


def test_pallas_3d_multistep_matches_sequential():
    import jax.numpy as jnp

    from heat_tpu.grid import initial_condition
    from heat_tpu.ops.pallas_stencil import (
        ftcs_multistep_edges_pallas,
        ftcs_step_edges_pallas,
    )

    cfg = HeatConfig(n=24, ndim=3, dtype="float32", ic="hat", sigma=0.15)
    T = jnp.asarray(initial_condition(cfg), jnp.float32)
    seq = T
    for _ in range(5):
        seq = ftcs_step_edges_pallas(seq, cfg.r)
    fused = ftcs_multistep_edges_pallas(T, cfg.r, 5)
    np.testing.assert_allclose(np.asarray(fused), np.asarray(seq),
                               rtol=0, atol=1e-6)


def test_pallas_on_unaligned_shape_matches_oracle():
    """Non-128-multiple grids run through the kernel via padding."""
    cfg = HeatConfig(n=100, ntime=9, dtype="float32", ic="hat")
    expect = solve(cfg.with_(backend="serial"))
    got = solve(cfg.with_(backend="pallas", fuse_steps=4))
    np.testing.assert_allclose(got.T, expect.T, rtol=0, atol=5e-6)


def test_pallas_kernel_on_tileable_shape():
    cfg = HeatConfig(n=128, ntime=10, dtype="float32", ic="hat")
    expect = solve(cfg.with_(backend="xla"))
    got = solve(cfg.with_(backend="pallas"))
    np.testing.assert_allclose(got.T, expect.T, rtol=0, atol=1e-6)


def test_multistep_kernel_matches_sequential():
    """Temporal blocking must be step-for-step identical to sequential."""
    import jax.numpy as jnp

    from heat_tpu.grid import initial_condition
    from heat_tpu.ops.pallas_stencil import (
        ftcs_multistep_edges_pallas,
        ftcs_multistep_ghost_pallas,
        ftcs_step_edges_pallas,
        ftcs_step_ghost_pallas,
    )

    cfg = HeatConfig(n=128, dtype="float32", ic="hat")
    T = jnp.asarray(initial_condition(cfg), jnp.float32)
    seq = T
    for _ in range(4):
        seq = ftcs_step_edges_pallas(seq, cfg.r)
    fused = ftcs_multistep_edges_pallas(T, cfg.r, 4)
    np.testing.assert_allclose(np.asarray(fused), np.asarray(seq),
                               rtol=0, atol=1e-6)

    seq_g = T
    for _ in range(3):
        seq_g = ftcs_step_ghost_pallas(seq_g, cfg.r, 1.0)
    fused_g = ftcs_multistep_ghost_pallas(T, cfg.r, 1.0, 3)
    np.testing.assert_allclose(np.asarray(fused_g), np.asarray(seq_g),
                               rtol=0, atol=1e-6)


@pytest.mark.parametrize("bc,ic", [("edges", "hat"), ("ghost", "uniform")])
def test_pallas_backend_with_fusion_matches_oracle(bc, ic):
    cfg = HeatConfig(n=128, ntime=21, dtype="float32", ic=ic, bc=bc,
                     backend="pallas", fuse_steps=4)  # 5 fused passes + 1
    expect = solve(cfg.with_(backend="serial"))
    got = solve(cfg)
    np.testing.assert_allclose(got.T, expect.T, rtol=0, atol=5e-6)


def test_multistep_fallback_when_k_exceeds_tile():
    import jax.numpy as jnp

    from heat_tpu.ops.pallas_stencil import (
        ftcs_multistep_edges_pallas,
        ftcs_step_edges_pallas,
    )

    T = jnp.ones((16, 128), jnp.float32).at[8, 60:70].set(2.0)
    # tile for a 16-row grid is at most 16 < 32 -> sequential fallback
    fused = ftcs_multistep_edges_pallas(T, 0.25, 32)
    seq = T
    for _ in range(32):
        seq = ftcs_step_edges_pallas(seq, 0.25)
    np.testing.assert_allclose(np.asarray(fused), np.asarray(seq),
                               rtol=0, atol=1e-6)


def test_heartbeat_and_zero_steps():
    cfg = BASE.with_(ntime=0)
    res = solve(cfg.with_(backend="xla"))
    np.testing.assert_array_equal(res.T, _oracle(cfg).T)
    res = solve(BASE.with_(backend="xla", ntime=7, heartbeat_every=3))
    assert res.timing.steps == 7


def test_unknown_backend():
    with pytest.raises(KeyError):
        get_backend("nccl")


def test_warm_exec_and_fetch_flags():
    cfg = HeatConfig(n=48, ntime=8, dtype="float32", backend="xla")
    plain = solve(cfg)
    warm = solve(cfg, warm_exec=True)
    np.testing.assert_allclose(warm.T, plain.T, rtol=0, atol=0)
    nofetch = solve(cfg, fetch=False)
    assert nofetch.T is None
    assert nofetch.timing.solve_s > 0


def test_async_shard_checkpoints_multihost(tmp_path, monkeypatch):
    """ISSUE 1: the multi-host ``save_shards`` path under the async
    pipeline — snapshot-and-continue must write the same per-process shard
    files as the sync path, and the resume must rebuild from them. Faked
    multi-host via the ``_addressable`` seam (tests/test_multihost.py)."""
    import heat_tpu.backends.common as common
    from heat_tpu.runtime import checkpoint

    monkeypatch.setattr(common, "_addressable", lambda x: False)
    da, ds = tmp_path / "async", tmp_path / "sync"
    cfg = HeatConfig(n=16, ntime=4, dtype="float32", backend="sharded",
                     mesh_shape=(2, 2), checkpoint_every=2)
    ra = solve(cfg.with_(checkpoint_dir=str(da)))
    assert ra.timing.overlap_s is not None     # the writer really ran
    solve(cfg.with_(checkpoint_dir=str(ds), async_io="off"))
    names = sorted(p.name for p in da.glob("heat_shards_step*.npz"))
    assert names == ["heat_shards_step00000002.proc0000.npz",
                     "heat_shards_step00000004.proc0000.npz"]
    for step in (2, 4):
        ba, sa = checkpoint.load_shards(cfg.with_(checkpoint_dir=str(da)),
                                        step)
        bs, ss = checkpoint.load_shards(cfg.with_(checkpoint_dir=str(ds)),
                                        step)
        assert sa == ss == step
        for (off_a, blk_a), (off_s, blk_s) in zip(ba, bs):
            assert off_a == off_s
            np.testing.assert_array_equal(blk_a, blk_s)
    # resume from the async-written shard files, bit-identical to a clean run
    res = solve(cfg.with_(checkpoint_dir=str(da), ntime=6))
    assert res.start_step == 4
    clean = solve(cfg.with_(ntime=6, checkpoint_every=0))
    np.testing.assert_array_equal(np.asarray(res.T_dev),
                                  np.asarray(clean.T_dev))


def test_async_checkpoint_solve_matches_plain_solve(tmp_path):
    """The snapshot copy must be donation-safe: a checkpointing async run's
    final field is bit-identical to a run with no checkpoints at all, on
    both single-device and sharded backends."""
    for backend, mesh in (("xla", None), ("sharded", (2, 2))):
        cfg = HeatConfig(n=16, ntime=8, dtype="float64", backend=backend,
                         mesh_shape=mesh)
        plain = solve(cfg)
        ck = solve(cfg.with_(checkpoint_every=2,
                             checkpoint_dir=str(tmp_path / backend)))
        np.testing.assert_array_equal(ck.T, plain.T)


def test_bounded_pallas_kernel_contract():
    """Bounded kernel with a discard margin >= ksteps reproduces the plain
    frozen-ring kernel on the interior it owns."""
    import jax.numpy as jnp

    from heat_tpu.ops.pallas_stencil import (
        _multistep,
        ftcs_multistep_bounded_pallas,
    )

    rng = np.random.default_rng(3)
    w = 4
    T = jnp.asarray(rng.random((40, 40)), jnp.float32)
    # reference: plain kernel freezes ring-1 of the same array
    ref = _multistep(T, 0.2, w)
    bounds = jnp.asarray([0, 39, 0, 39], jnp.int32)
    got = ftcs_multistep_bounded_pallas(T, 0.2, w, bounds)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=0, atol=0)
    # freeze-nothing bounds: only the interior >= w cells from every edge is
    # trustworthy (the caller's discard margin)
    open_bounds = jnp.asarray([-1, 40, -1, 40], jnp.int32)
    got2 = ftcs_multistep_bounded_pallas(T, 0.2, w, open_bounds)
    # compare against w unconstrained FTCS steps computed densely in numpy
    dense = np.asarray(T, np.float64)
    for _ in range(w):
        nxt = dense.copy()
        nxt[1:-1, 1:-1] = dense[1:-1, 1:-1] + 0.2 * (
            dense[2:, 1:-1] + dense[:-2, 1:-1] + dense[1:-1, 2:]
            + dense[1:-1, :-2] - 4 * dense[1:-1, 1:-1])
        dense = nxt
    inner = slice(w, -w)
    np.testing.assert_allclose(np.asarray(got2)[inner, inner],
                               dense[inner, inner], rtol=0, atol=2e-6)
