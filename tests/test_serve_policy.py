"""Admission-policy layer (serve/policy.py) + incremental results seam.

Three contracts, all socket-free:

- **fifo regression**: extracting the queue behind the policy interface
  must not move a single admission — the 64-request golden trace below
  was captured from the PR-5 scheduler (deque-based, pre-policy) and the
  refactored engine must reproduce it bit-for-bit.
- **SLO policies**: EDF admits a later-submitted tighter-deadline request
  first (and interactive-class ahead of standard regardless of
  deadlines); fair share keeps a flooding tenant from starving another
  past its weight; per-tenant quotas shed with a structured
  ``overloaded`` record.
- **incremental consumption** (the gateway's seam): listeners fire at
  each request's terminal transition — before the drain finishes — and
  ``poll``/``wait`` observe records while the engine keeps running.
"""

import threading

import numpy as np
import pytest

from heat_tpu.backends import solve
from heat_tpu.config import HeatConfig
from heat_tpu.runtime import faults
from heat_tpu.serve import Engine, ServeConfig
from heat_tpu.serve import policy as policy_mod
from heat_tpu.serve.engine import LaneEngine


@pytest.fixture(autouse=True)
def _fresh_faults():
    faults.reset()
    yield
    faults.reset()


def quiet(**kw) -> ServeConfig:
    kw.setdefault("emit_records", False)
    return ServeConfig(**kw)


# --- fifo bit-identity regression -------------------------------------------

# Captured from the PR-5 scheduler (hard-coded deque admission) on the
# exact wave below: the (lane, ntime) pairs LaneEngine.load_lane saw, in
# order. ntimes are unique, so the pairs identify each request's
# admission slot exactly. The policy refactor must not move ONE of them.
GOLDEN_NTIMES = [3 + ((37 * i + 11) % 64) * 2 + (i % 2) for i in range(64)]
GOLDEN_TRACE = [
    [0, 25], [1, 100], [2, 45], [3, 120], [0, 65], [2, 12], [2, 85],
    [1, 32], [0, 105], [3, 52], [1, 125], [2, 72], [3, 17], [3, 92],
    [0, 37], [2, 112], [0, 57], [1, 4], [1, 77], [3, 24], [0, 97],
    [3, 44], [2, 117], [1, 64], [3, 9], [3, 84], [1, 29], [0, 104],
    [1, 49], [2, 124], [3, 69], [1, 16], [0, 89], [1, 36], [3, 109],
    [1, 56], [2, 129], [0, 76], [1, 21], [1, 96], [3, 41], [0, 116],
    [2, 61], [3, 8], [3, 81], [1, 28], [2, 101], [1, 48], [0, 121],
    [3, 68], [1, 13], [1, 88], [2, 33], [3, 108], [2, 53], [0, 128],
    [1, 73], [2, 20], [3, 93], [2, 40], [1, 113], [2, 60], [0, 5],
    [0, 80]]


def test_fifo_policy_admission_trace_bit_identical_to_pre_policy_engine():
    """Satellite regression: same 64-request wave -> identical lane
    assignment trace as the hard-coded-deque scheduler (golden captured
    at PR 5). Locks both the pop ORDER (fifo == submit order) and the
    lane placement the continuous-batching refill derives from it."""
    trace = []
    real = LaneEngine.load_lane

    def spy(self, lane, field, r, steps, bc_value):
        trace.append([int(lane), int(steps)])
        return real(self, lane, field, r, steps, bc_value)

    cfgs = [HeatConfig(n=12, ntime=t, dtype="float64")
            for t in GOLDEN_NTIMES]
    eng = Engine(quiet(lanes=4, chunk=8, buckets=(16,)))  # policy="fifo"
    ids = [eng.submit(c) for c in cfgs]
    LaneEngine.load_lane = spy
    try:
        recs = {r["id"]: r for r in eng.results()}
    finally:
        LaneEngine.load_lane = real
    assert all(recs[i]["status"] == "ok" for i in ids)
    assert trace == GOLDEN_TRACE
    # the engine-side admission order agrees: fifo == submit order
    assert eng.admission_trace == ids


# --- EDF --------------------------------------------------------------------


def fake_clock(monkeypatch, step=0.001):
    """Deterministic wall clock: every read advances ``step`` seconds.
    Patches the one seam both scheduler and engine timestamps use."""
    import heat_tpu.serve.scheduler as sched_mod

    t = {"now": 0.0}

    def clock():
        t["now"] += step
        return t["now"]

    monkeypatch.setattr(sched_mod, "wall_clock", clock)
    return t


def test_edf_admits_later_submitted_tighter_deadline_first(monkeypatch):
    """Acceptance (fake clock): A (loose deadline) is submitted before
    B (tight deadline), and an undated request before both. FIFO would
    admit them in submit order — EDF must admit B first and the undated
    request last, because the deadline now shapes admission order, not
    just shedding."""
    fake_clock(monkeypatch)
    eng = Engine(quiet(lanes=1, chunk=4, buckets=(16,), policy="edf"))
    undated = eng.submit(HeatConfig(n=16, ntime=12, dtype="float64"))
    a = eng.submit(HeatConfig(n=16, ntime=4, dtype="float64"),
                   deadline_ms=50_000_000.0)
    b = eng.submit(HeatConfig(n=16, ntime=4, dtype="float64"),
                   deadline_ms=2_000_000.0)
    recs = {r["id"]: r for r in eng.results()}
    assert all(r["status"] == "ok" for r in recs.values()), recs
    assert eng.admission_trace == [b, a, undated]


def test_edf_class_priority_outranks_deadline(monkeypatch):
    """interactive > standard > batch strictly: an interactive request
    with NO deadline still beats a standard request with a tight one;
    undated requests of one class keep submit order among themselves."""
    fake_clock(monkeypatch)
    eng = Engine(quiet(lanes=1, chunk=4, buckets=(16,), policy="edf"))
    undated_std = eng.submit(HeatConfig(n=16, ntime=12, dtype="float64"))
    batch = eng.submit(HeatConfig(n=16, ntime=4, dtype="float64"),
                       slo_class="batch", deadline_ms=1_000_000.0)
    std = eng.submit(HeatConfig(n=16, ntime=4, dtype="float64"),
                     deadline_ms=2_000_000.0)
    inter = eng.submit(HeatConfig(n=16, ntime=4, dtype="float64"),
                       slo_class="interactive")
    recs = {r["id"]: r for r in eng.results()}
    assert all(r["status"] == "ok" for r in recs.values())
    # interactive first despite no deadline; dated standard before the
    # undated one; dated batch dead last despite the tightest deadline
    assert eng.admission_trace == [inter, std, undated_std, batch]
    assert recs[inter]["class"] == "interactive"


def test_edf_without_deadlines_degrades_to_fifo():
    eng = Engine(quiet(lanes=1, chunk=4, buckets=(16,), policy="edf"))
    ids = [eng.submit(HeatConfig(n=16, ntime=4 + i, dtype="float64"))
           for i in range(5)]
    recs = {r["id"]: r for r in eng.results()}
    assert all(r["status"] == "ok" for r in recs.values())
    assert eng.admission_trace == ids


# --- fair share -------------------------------------------------------------


def _tenant_wave(eng, tenant, count, ntime=6):
    return [eng.submit(HeatConfig(n=16, ntime=ntime, dtype="float64"),
                       request_id=f"{tenant}-{i}", tenant=tenant)
            for i in range(count)]


def test_fair_share_flood_cannot_starve_equal_weight_tenant():
    """Acceptance: tenant 'flood' queues 8 requests before 'small'
    queues 4 — under equal weights the admissions must interleave, so
    every 'small' request is admitted within the first 2*4+1 slots
    instead of waiting out the whole flood (FIFO's behavior)."""
    eng = Engine(quiet(lanes=1, chunk=4, buckets=(16,), policy="fair"))
    _tenant_wave(eng, "flood", 8)
    _tenant_wave(eng, "small", 4)
    recs = {r["id"]: r for r in eng.results()}
    assert all(r["status"] == "ok" for r in recs.values())
    trace = eng.admission_trace
    small_slots = [i for i, rid in enumerate(trace)
                   if rid.startswith("small")]
    assert max(small_slots) <= 8, trace   # strict alternation lands 1,3,5,7
    # and FIFO on the same wave WOULD starve: all flood first
    fifo = Engine(quiet(lanes=1, chunk=4, buckets=(16,)))
    _tenant_wave(fifo, "flood", 8)
    _tenant_wave(fifo, "small", 4)
    fifo.results()
    assert [r for r in fifo.admission_trace[:8]] == \
        [f"flood-{i}" for i in range(8)]


def test_fair_share_respects_weights():
    """weights vip=3, flood=1: while both are backlogged vip takes ~3 of
    every 4 admissions (virtual time advances by work/weight)."""
    eng = Engine(quiet(lanes=1, chunk=4, buckets=(16,), policy="fair",
                       tenant_weights=(("vip", 3.0), ("flood", 1.0))))
    _tenant_wave(eng, "flood", 6)
    _tenant_wave(eng, "vip", 6)
    recs = {r["id"]: r for r in eng.results()}
    assert all(r["status"] == "ok" for r in recs.values())
    first8 = eng.admission_trace[:8]
    vip_share = sum(1 for rid in first8 if rid.startswith("vip"))
    assert vip_share >= 5, eng.admission_trace  # 3:1 weighting -> ~6 of 8


def test_fair_share_idle_tenant_cannot_bank_credit():
    """A tenant that sat idle while another was served must re-enter at
    the current virtual time, not replay its unused share in a burst."""
    q = policy_mod.FairShareQueue({})

    class R:  # minimal Request stand-in
        def __init__(self, tenant, seq):
            self.tenant, self.seq = tenant, seq
            self.slo_class, self.deadline_t = "standard", None
            self.cfg = HeatConfig(n=16, ntime=4, dtype="float64")

    for i in range(4):
        q.push(R("busy", i))
    served = [q.pop().tenant for _ in range(3)]   # busy accrues vtime
    q.push(R("idler", 100))
    q.push(R("idler", 101))
    # idler was raised to busy's floor: busy is not immediately locked
    # out by the idler's banked zero-credit (without catch-up the order
    # would be idler, idler, busy)
    order = [q.pop().tenant for _ in range(3)]
    assert served == ["busy"] * 3
    assert order == ["busy", "idler", "idler"]


# --- per-tenant quota -------------------------------------------------------


def test_tenant_quota_sheds_with_structured_overloaded_record():
    """Acceptance: a tenant past its --tenant-quota gets 'overloaded'
    records naming the tenant, while another tenant (and the global
    queue) keep admitting."""
    eng = Engine(quiet(lanes=1, chunk=4, buckets=(16,), tenant_quota=2))
    noisy = _tenant_wave(eng, "noisy", 5)
    polite = _tenant_wave(eng, "polite", 1)
    recs = {r["id"]: r for r in eng.results()}
    shed = [rid for rid in noisy if recs[rid]["status"] == "rejected"]
    assert len(shed) == 3
    for rid in shed:
        assert "overloaded" in recs[rid]["error"]
        assert "noisy" in recs[rid]["error"]
        assert "tenant-quota" in recs[rid]["error"]
    assert recs[polite[0]]["status"] == "ok"
    assert eng.shed == 3
    assert sum(recs[rid]["status"] == "ok" for rid in noisy) == 2


def test_tenant_and_class_validation_at_submit():
    eng = Engine(quiet(buckets=(16,)))
    with pytest.raises(ValueError, match="tenant"):
        eng.submit(HeatConfig(n=8, ntime=1), tenant="no spaces!")
    with pytest.raises(ValueError, match="class"):
        eng.submit(HeatConfig(n=8, ntime=1), slo_class="premium")
    with pytest.raises(ValueError, match="policy"):
        ServeConfig(policy="lifo")
    with pytest.raises(ValueError, match="weight"):
        ServeConfig(tenant_weights=(("a", 0.0),))
    with pytest.raises(ValueError, match="tenant_quota"):
        ServeConfig(tenant_quota=-1)


# --- incremental consumption (poll / wait / listeners) ----------------------


def test_listener_fires_at_lane_retirement_not_at_drain():
    """Satellite: the results-ready seam delivers the short request's
    record while the long request is still stepping — the gateway can
    stream it immediately instead of waiting for full drain."""
    eng = Engine(quiet(lanes=2, chunk=8, buckets=(16,)))
    short = eng.submit(HeatConfig(n=16, ntime=8, dtype="float64"))
    long_ = eng.submit(HeatConfig(n=16, ntime=800, dtype="float64"))
    events = []

    def listener(rec):
        with eng._lock:
            others = {r["id"]: r["status"] for r in eng._records}
        events.append((rec["id"], rec["status"], others))

    eng.add_listener(listener)
    eng.results()
    eng.remove_listener(listener)
    assert [e[0] for e in events] == [short, long_]
    sid, sstatus, others_at_short = events[0]
    assert sstatus == "ok"
    # when the short record fired, the long solve had NOT finished
    assert others_at_short[long_] not in ("ok", "error")


def test_poll_and_wait_while_engine_runs_online():
    """poll() observes a live record without draining; wait() blocks to
    the terminal record; results() refuses while the online scheduler
    owns the queue (poll/wait are the online API)."""
    eng = Engine(quiet(lanes=1, chunk=8, buckets=(16,))).start()
    try:
        rid = eng.submit(HeatConfig(n=16, ntime=30, dtype="float64"))
        with pytest.raises(RuntimeError, match="online"):
            eng.results()
        rec = eng.wait(rid, timeout=60)
        assert rec is not None and rec["status"] == "ok"
        assert "T" not in rec            # snapshots carry no field payload
        assert eng.poll(rid)["status"] == "ok"
        assert eng.poll("nope") is None
        with pytest.raises(KeyError):
            eng.wait("nope", timeout=1)
        # online bit-identity: submitted AFTER start, equals the solo run
        cfg = HeatConfig(n=16, ntime=11, dtype="float64", nu=0.1)
        rid2 = eng.submit(cfg)
        assert eng.wait(rid2, timeout=60)["status"] == "ok"
        with eng._lock:
            rec2 = dict(eng._by_id[rid2])
    finally:
        assert eng.shutdown(timeout=60)
    np.testing.assert_array_equal(rec2["T"], solve(cfg).T)
    assert eng.timing is not None        # the online loop stamps Timing


def test_shutdown_idempotent_and_safe_without_start():
    eng = Engine(quiet(buckets=(16,)))
    assert eng.shutdown() is True        # never started: a no-op
    eng.start()
    eng.start()                          # idempotent while running
    assert eng.shutdown(timeout=30) is True
    assert eng.shutdown(timeout=30) is True


# --- histogram primitive ----------------------------------------------------


def test_histogram_buckets_and_quantiles():
    h = policy_mod.Histogram(buckets=(0.1, 1.0, 10.0))
    for v in (0.05, 0.5, 0.5, 5.0, 50.0):
        h.observe(v)
    snap = h.snapshot()
    assert snap["count"] == 5 and snap["sum"] == pytest.approx(56.05)
    assert snap["buckets"] == [("0.1", 1), ("1", 3), ("10", 4),
                               ("+Inf", 5)]
    assert h.quantile(0.5) == 1.0
    assert h.quantile(0.99) == float("inf")
    assert policy_mod.Histogram().quantile(0.5) is None


def test_summary_and_metrics_carry_policy_fields():
    eng = Engine(quiet(lanes=1, chunk=4, buckets=(16,), policy="edf"))
    eng.submit(HeatConfig(n=16, ntime=4, dtype="float64"),
               slo_class="interactive")
    eng.results()
    s = eng.summary()
    assert s["policy"] == "edf" and s["lane_grows"] == 0
    assert s["queued_now"] == 0
    assert "interactive" in eng.lat_hist
    assert eng.lat_hist["interactive"].snapshot()["count"] == 1
    assert eng.depth_hist.snapshot()["count"] == 1
    assert eng.timing.serve_policy == "edf"
    assert any("policy edf" in l for l in eng.timing.report_lines())
