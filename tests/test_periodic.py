"""Periodic ("pbc") topology: the reference's cartesian communicator is
built to carry periodic boundaries but hardcodes them off (``pbc = .false.``
fed to ``mpi_cart_create`` periods, fortran/mpi+cuda/heat.F90:76,97). This
framework enables the topology as ``bc="periodic"``: wrap-around neighbors
everywhere, closed ppermute ring in the sharded backend, nothing pinned.

With no boundary there is no boundary flux, so the global temperature sum —
the invariant behind the reference's commented-out MPI_Reduce debug check
(:266-273) — is conserved EXACTLY, which these tests assert.
"""

import numpy as np
import pytest

from heat_tpu.backends import solve
from heat_tpu.config import HeatConfig

BASE = HeatConfig(n=32, ntime=12, dtype="float64", bc="periodic", ic="hat")


def oracle_periodic(T, r, steps):
    """Literal modular-index transcription of the FTCS update on the torus
    (independent of every framework path: explicit gather, reference
    summation order)."""
    T = np.array(T, np.float64)
    n_ax = T.shape
    nd = T.ndim
    idx = np.indices(n_ax)
    for _ in range(steps):
        old = T.copy()
        acc = None
        for off in (1, -1):
            for d in range(nd):
                sl = list(idx)
                sl[d] = (idx[d] + off) % n_ax[d]
                v = old[tuple(sl)]
                acc = v if acc is None else acc + v
        T = old + r * (acc + (-2.0 * nd) * old)
    return T


def test_serial_periodic_matches_literal_oracle():
    cfg = BASE.with_(backend="serial")
    got = solve(cfg)
    ref = oracle_periodic(solve(cfg.with_(ntime=0)).T, cfg.r, cfg.ntime)
    np.testing.assert_allclose(got.T, ref, rtol=0, atol=0)


def test_xla_periodic_matches_serial_bitwise():
    expect = solve(BASE.with_(backend="serial"))
    got = solve(BASE.with_(backend="xla"))
    np.testing.assert_allclose(got.T, expect.T, rtol=0, atol=0)


def test_periodic_conserves_total_heat_exactly():
    """No boundary -> no flux: the sum invariant the reference's dead
    MPI_Reduce check was reaching for, exact on the torus."""
    for backend in ("serial", "xla"):
        cfg = BASE.with_(backend=backend, report_sum=True)
        before = float(np.sum(solve(cfg.with_(ntime=0)).T, dtype=np.float64))
        after = solve(cfg).gsum
        assert after == pytest.approx(before, rel=1e-13)


def test_periodic_translation_invariance():
    """Rolling the IC rolls the solution — only true on the torus."""
    cfg = BASE.with_(backend="xla")
    base = solve(cfg)
    T0 = solve(cfg.with_(ntime=0)).T
    rolled = solve(cfg, T0=np.roll(T0, (5, -7), axis=(0, 1)))
    np.testing.assert_allclose(
        rolled.T, np.roll(base.T, (5, -7), axis=(0, 1)), rtol=0, atol=0)


def test_pallas_periodic_matches_serial():
    """Fused wrap-ghost Pallas multistep (interpret mode on CPU) vs the
    sequential oracle, single-step and fused."""
    for fuse in (1, 4):
        cfg = BASE.with_(backend="pallas", dtype="float32", fuse_steps=fuse)
        expect = solve(cfg.with_(backend="serial", dtype="float32"))
        got = solve(cfg)
        np.testing.assert_allclose(got.T, expect.T, rtol=0, atol=2e-6)


def test_pallas_periodic_3d():
    cfg = HeatConfig(n=16, ndim=3, ntime=4, dtype="float32", sigma=1 / 6,
                     bc="periodic", ic="hat", backend="pallas", fuse_steps=2)
    expect = solve(cfg.with_(backend="serial"))
    got = solve(cfg)
    np.testing.assert_allclose(got.T, expect.T, rtol=0, atol=2e-6)


@pytest.mark.parametrize("mesh_shape", [(8, 1), (2, 4)])
def test_sharded_periodic_matches_serial(mesh_shape):
    """The closed ppermute ring: decomposed torus == undecomposed torus,
    bit-for-bit in f64 (same summands, same order)."""
    cfg = BASE.with_(backend="sharded", mesh_shape=mesh_shape)
    expect = solve(cfg.with_(backend="serial", mesh_shape=None))
    got = solve(cfg)
    np.testing.assert_allclose(got.T, expect.T, rtol=0, atol=0)


def test_sharded_periodic_fused_and_staged():
    cfg = BASE.with_(backend="sharded", mesh_shape=(2, 4), ntime=11)
    per_step = solve(cfg.with_(fuse_steps=1))
    fused = solve(cfg.with_(fuse_steps=4))
    np.testing.assert_allclose(fused.T, per_step.T, rtol=0, atol=0)
    staged = solve(cfg.with_(fuse_steps=4, comm="staged"))
    np.testing.assert_allclose(staged.T, per_step.T, rtol=0, atol=0)


def test_sharded_periodic_pallas_local_kernel():
    cfg = BASE.with_(backend="sharded", mesh_shape=(2, 4), dtype="float32",
                     local_kernel="pallas", fuse_steps=3)
    expect = solve(cfg.with_(backend="serial", mesh_shape=None,
                             local_kernel="auto"))
    got = solve(cfg)
    np.testing.assert_allclose(got.T, expect.T, rtol=0, atol=2e-6)


def test_sharded_periodic_3d():
    cfg = HeatConfig(n=16, ndim=3, ntime=5, dtype="float64", sigma=0.15,
                     bc="periodic", ic="hat", backend="sharded",
                     mesh_shape=(2, 2, 2))
    expect = solve(cfg.with_(backend="serial", mesh_shape=None))
    got = solve(cfg)
    np.testing.assert_allclose(got.T, expect.T, rtol=0, atol=1e-14)


def test_parity_order_periodic_ic_start_matches_default():
    """Literal update-then-swap on the torus: IC starts seed ghosts with one
    exchange, so the orders coincide (same equivalence as the Dirichlet
    case, tests/test_parity_order.py)."""
    cfg = BASE.with_(backend="sharded", mesh_shape=(2, 4), ntime=7)
    default = solve(cfg)
    parity = solve(cfg.with_(parity_order=True))
    np.testing.assert_allclose(parity.T, default.T, rtol=0, atol=0)


def test_cli_periodic(tmp_cwd, capsys):
    from heat_tpu.cli import main

    (tmp_cwd / "input.dat").write_text("24 0.25 0.05 2.0 5 0\n")
    assert main(["run", "--backend", "xla", "--bc", "periodic",
                 "--report-sum"]) == 0
    out = capsys.readouterr().out
    assert "simulation completed!!!!" in out

    assert main(["plan", "--backend", "sharded", "--bc", "periodic",
                 "--dtype", "float32"]) == 0
    out = capsys.readouterr().out
    assert "periodic (torus)" in out


def test_periodic_steady_state_is_ic_mean():
    """t->inf on the torus: conservation forces the uniform IC mean (the
    Dirichlet families instead drain to bc_value — models/heat.py)."""
    from heat_tpu.models import get_model

    cfg = BASE.with_(backend="xla", n=16, ntime=4000)
    T0 = solve(cfg.with_(ntime=0)).T
    res = solve(cfg)
    np.testing.assert_allclose(
        res.T, get_model(cfg).steady_state(cfg, T0), atol=1e-9)
    with pytest.raises(ValueError, match="IC mean"):
        get_model(cfg).steady_state(cfg)


def test_periodic_remainder_chunk_gates_per_shape(monkeypatch):
    """ADVICE r2 #4: the periodic multistep's remainder chunk wrap-pads by
    k < cap; if the kernel has no plan for that smaller shape the dispatch
    must fall back to XLA rather than hit _multistep's assert."""
    import jax.numpy as jnp

    import heat_tpu.ops.pallas_stencil as ps

    n = 24
    shape = (n, n, n)
    rng = np.random.RandomState(0)
    T = jnp.asarray(rng.rand(*shape), jnp.float32)
    ksteps = 20
    cap = ps.periodic_pad_width(shape, ksteps)
    rem = ksteps % cap
    assert 0 < rem < cap  # the test needs a genuine remainder chunk
    blocked = tuple(s + 2 * rem for s in shape)
    real = ps.pallas_available
    monkeypatch.setattr(
        ps, "pallas_available",
        lambda shp, dt: tuple(shp) != blocked and real(shp, dt))
    out = ps.ftcs_multistep_periodic_pallas(T, 0.1, ksteps)
    ref = T
    for _ in range(ksteps):
        ref = ps.ftcs_step_periodic(ref, 0.1)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))
