"""Gateway lifecycle over a real localhost socket (serve/gateway.py).

Every connection carries a timeout and every wait is bounded, so the
suite cannot wedge tier-1: a hang is a failure, not a stall. The
load-bearing contracts:

- streaming admission: POSTs land in a RUNNING engine (submitted after
  the scheduler started) and their results are bit-identical to solo
  ``drive()`` runs of the same configs;
- ``/healthz`` flips 200 -> 503 the moment ``/drainz`` is called, while
  in-flight lanes still finish (graceful, idempotent drain);
- ``--max-queue`` pressure answers 429 + ``Retry-After`` instead of
  queueing without bound;
- a ``lane-nan`` fault surfaces through HTTP as the same structured
  ``nonfinite`` record the JSONL drain emits (the PR-5 fault-domain
  contract survives the transport).
"""

import json
import threading
import urllib.error
import urllib.request

import numpy as np
import pytest

from heat_tpu.backends import solve
from heat_tpu.config import HeatConfig
from heat_tpu.runtime import faults
from heat_tpu.serve import Engine, ServeConfig
from heat_tpu.serve.gateway import Gateway, render_metrics

TIMEOUT = 60   # every socket op and drain wait is bounded by this


@pytest.fixture(autouse=True)
def _fresh_faults():
    faults.reset()
    yield
    faults.reset()


def make_gateway(tmp_path=None, start_engine=True, **scfg_kw):
    scfg_kw.setdefault("emit_records", False)
    scfg_kw.setdefault("lanes", 2)
    scfg_kw.setdefault("chunk", 8)
    scfg_kw.setdefault("buckets", (32,))
    if tmp_path is not None:
        scfg_kw.setdefault("out_dir", str(tmp_path / "results"))
    eng = Engine(ServeConfig(**scfg_kw))
    gw = Gateway(eng, "127.0.0.1", 0, start_engine=start_engine).start()
    return gw, eng


def http(gw, method, path, body=None, timeout=TIMEOUT):
    """One bounded request; returns (status, parsed-lines, headers)."""
    req = urllib.request.Request(
        f"http://{gw.address}{path}",
        data=body.encode() if body is not None else None, method=method)
    try:
        resp = urllib.request.urlopen(req, timeout=timeout)
        status, raw, headers = resp.status, resp.read(), resp.headers
    except urllib.error.HTTPError as e:
        status, raw, headers = e.code, e.read(), e.headers
    lines = [json.loads(l) for l in raw.decode().splitlines() if l.strip()]
    return status, lines, headers


def line(**kw) -> str:
    return json.dumps(kw) + "\n"


# --- streaming admission + end-to-end bit-identity ---------------------------


def test_streaming_admission_bit_identical_to_solo_runs(tmp_path):
    """Acceptance e2e: requests POSTed while lanes run (engine already
    started, first request mid-flight) come back ok with npz outputs
    bit-identical to solo drive() runs — including concurrent POSTs."""
    gw, eng = make_gateway(tmp_path, keep_fields=True)
    try:
        # first request starts the lanes turning
        st, lines0, _ = http(gw, "POST",
                             "/v1/solve?wait=0",
                             line(id="warm", n=16, ntime=300,
                                  dtype="float64"))
        assert st == 202 and lines0[0]["accepted"] == ["warm"]
        assert eng.online
        # two concurrent streaming POSTs while the first one runs
        cfg_a = dict(id="a", n=16, ntime=24, dtype="float64", nu=0.1)
        cfg_b = dict(id="b", n=24, ntime=12, dtype="float64", bc="ghost",
                     ic="uniform")
        results = {}

        def post(name, payload):
            st2, recs, _ = http(gw, "POST", "/v1/solve", line(**payload))
            results[name] = (st2, recs)

        threads = [threading.Thread(target=post, args=("a", cfg_a)),
                   threading.Thread(target=post, args=("b", cfg_b))]
        for t in threads:
            t.start()
        for t in threads:
            t.join(TIMEOUT)
            assert not t.is_alive()
        for name in ("a", "b"):
            st2, recs = results[name]
            assert st2 == 200
            (rec,) = recs
            assert rec["id"] == name and rec["status"] == "ok", rec
            assert "T" not in rec       # stream carries records, not fields
        assert eng.wait("warm", timeout=TIMEOUT)["status"] == "ok"
    finally:
        gw.request_drain()
        assert gw.wait_drained(TIMEOUT)
        gw.close()
    # bit-identity against solo runs, through the npz the gateway wrote
    out = tmp_path / "results"
    for rid, cfg in (("warm", HeatConfig(n=16, ntime=300, dtype="float64")),
                     ("a", HeatConfig(n=16, ntime=24, dtype="float64",
                                      nu=0.1)),
                     ("b", HeatConfig(n=24, ntime=12, dtype="float64",
                                      bc="ghost", ic="uniform"))):
        with np.load(out / f"{rid}.npz") as z:
            np.testing.assert_array_equal(z["T"], solve(cfg).T)


def test_poll_endpoint_and_unknown_routes(tmp_path):
    gw, _ = make_gateway(tmp_path)
    try:
        st, recs, _ = http(gw, "POST", "/v1/solve",
                           line(id="x", n=16, ntime=8, dtype="float64"))
        assert st == 200 and recs[0]["status"] == "ok"
        st, (rec,), _ = http(gw, "GET", "/v1/requests/x")
        assert st == 200 and rec["status"] == "ok" and rec["id"] == "x"
        st, _, _ = http(gw, "GET", "/v1/requests/nope")
        assert st == 404
        st, _, _ = http(gw, "GET", "/no/such/route")
        assert st == 404
        st, (err,), _ = http(gw, "POST", "/v1/solve", "")
        assert st == 400 and "empty body" in err["error"]
        # a malformed line is a per-line rejection, not a dropped batch
        st, recs, _ = http(gw, "POST", "/v1/solve",
                           "this is not json\n"
                           + line(id="y", n=16, ntime=4, dtype="float64"))
        assert st == 200
        by_status = {r["status"] for r in recs}
        assert by_status == {"rejected", "ok"}
    finally:
        gw.request_drain()
        assert gw.wait_drained(TIMEOUT)
        gw.close()


# --- drain lifecycle ---------------------------------------------------------


def test_healthz_flips_during_drain_and_inflight_finishes(tmp_path):
    """/drainz stops admission immediately (healthz 503, solve 503 +
    Retry-After) while the in-flight lane finishes; drain is
    idempotent and the drained request stays pollable."""
    gw, eng = make_gateway(tmp_path)
    try:
        st, _, _ = http(gw, "GET", "/healthz")
        assert st == 200
        st, _, _ = http(gw, "POST", "/v1/solve?wait=0",
                        line(id="inflight", n=16, ntime=200,
                             dtype="float64"))
        assert st == 202
        st, (d,), _ = http(gw, "POST", "/drainz")
        assert st == 200 and d["draining"] is True
        st, (h,), hdrs = http(gw, "GET", "/healthz")
        assert st == 503 and h["status"] == "draining"
        assert hdrs.get("Retry-After") is not None
        st, _, hdrs = http(gw, "POST", "/v1/solve",
                           line(n=16, ntime=4, dtype="float64"))
        assert st == 503 and hdrs.get("Retry-After") is not None
        assert gw.wait_drained(TIMEOUT)
        # the in-flight request finished, not aborted: graceful drain
        st, (rec,), _ = http(gw, "GET", "/v1/requests/inflight")
        assert st == 200 and rec["status"] == "ok"
        # idempotent: a second drainz reports drained
        st, (d2,), _ = http(gw, "POST", "/drainz")
        assert st == 200 and d2["drained"] is True
        assert not eng.online
    finally:
        gw.close()


# --- backpressure ------------------------------------------------------------


def test_429_retry_after_under_max_queue_pressure():
    """--max-queue pressure: with the scheduler deliberately held (not
    started), the queue bound is deterministic — the first request
    queues, the second answers 429 with a Retry-After hint and a
    structured overloaded record."""
    gw, eng = make_gateway(max_queue=1, start_engine=False)
    try:
        st, _, _ = http(gw, "POST", "/v1/solve?wait=0",
                        line(id="q1", n=16, ntime=8, dtype="float64"))
        assert st == 202
        st, (body,), hdrs = http(gw, "POST", "/v1/solve?wait=0",
                                 line(id="q2", n=16, ntime=8,
                                      dtype="float64"))
        assert st == 429
        assert hdrs.get("Retry-After") is not None
        assert int(hdrs["Retry-After"]) >= 1
        (rec,) = body["records"]
        assert rec["status"] == "rejected"
        assert rec["error"].startswith("overloaded")
        assert eng.shed == 1
        # draining the engine serves the queued request normally
        eng.start()
        assert eng.wait("q1", timeout=TIMEOUT)["status"] == "ok"
    finally:
        gw.request_drain()
        assert gw.wait_drained(TIMEOUT)
        gw.close()


# --- fault domains through HTTP ----------------------------------------------


def test_lane_nan_fault_surfaces_as_structured_http_record(tmp_path):
    """PR-5 contract through the transport: a lane-nan-poisoned request
    streams back (and polls) as a structured nonfinite record; its
    co-scheduled neighbor is untouched and no NaN npz is published."""
    gw, eng = make_gateway(tmp_path, inject="lane-nan@10:req=bad")
    try:
        st, recs, _ = http(
            gw, "POST", "/v1/solve",
            line(id="bad", n=16, ntime=40, dtype="float64")
            + line(id="good", n=16, ntime=40, dtype="float64", nu=0.1))
        assert st == 200
        by_id = {r["id"]: r for r in recs}
        assert by_id["bad"]["status"] == "nonfinite"
        assert "non-finite field detected at ~step" in by_id["bad"]["error"]
        assert by_id["good"]["status"] == "ok"
        st, (rec,), _ = http(gw, "GET", "/v1/requests/bad")
        assert st == 200 and rec["status"] == "nonfinite"
        assert eng.lanes_quarantined == 1
    finally:
        gw.request_drain()
        assert gw.wait_drained(TIMEOUT)
        gw.close()
    assert not (tmp_path / "results" / "bad.npz").exists()
    assert (tmp_path / "results" / "good.npz").exists()


# --- /metrics ----------------------------------------------------------------


def test_metrics_surface_over_http_and_inline(tmp_path):
    gw, eng = make_gateway(tmp_path, tenant_quota=8)
    try:
        st, _, _ = http(gw, "POST", "/v1/solve",
                        line(id="m1", n=16, ntime=8, dtype="float64",
                             tenant="acme", **{"class": "interactive"})
                        + line(id="m2", n=16, ntime=8, dtype="float64"))
        assert st == 200
        resp = urllib.request.urlopen(
            f"http://{gw.address}/metrics", timeout=TIMEOUT)
        assert resp.status == 200
        assert resp.headers["Content-Type"].startswith("text/plain")
        scraped = resp.read().decode()
        assert 'heat_tpu_serve_requests_total{status="ok"} 2' in scraped
    finally:
        gw.request_drain()
        assert gw.wait_drained(TIMEOUT)
        gw.close()
    text = render_metrics(eng)
    assert 'heat_tpu_serve_requests_total{status="ok"} 2' in text
    assert 'heat_tpu_serve_info{policy="fifo"' in text
    assert ('heat_tpu_serve_request_latency_seconds_bucket'
            '{class="interactive",le="+Inf"} 1') in text
    assert ('heat_tpu_serve_request_latency_seconds_count'
            '{class="standard"} 1') in text
    assert "heat_tpu_serve_queue_depth_observed_bucket" in text
    assert "heat_tpu_serve_shed_total 0" in text
    assert "heat_tpu_serve_draining 1" in text
    # build identity + uptime ride every scrape
    import heat_tpu

    assert (f'heat_tpu_build_info{{version="{heat_tpu.__version__}"'
            in text)
    assert "heat_tpu_process_uptime_seconds" in text
    uptime = [l for l in text.splitlines()
              if l.startswith("heat_tpu_process_uptime_seconds ")]
    assert uptime and float(uptime[0].split()[1]) > 0


def test_metrics_escapes_user_supplied_label_values():
    """Satellite regression: tenant/class are user-supplied strings — a
    tenant named with backslashes/quotes/newlines must not corrupt the
    exposition format. Tenant names are charset-validated at admission,
    so exercise render_metrics directly via the queue-depth counter."""
    from heat_tpu.serve.gateway import escape_label_value

    assert escape_label_value('a"b') == 'a\\"b'
    assert escape_label_value("a\\b\nc") == "a\\\\b\\nc"
    eng = Engine(ServeConfig(emit_records=False, buckets=(16,)))
    evil = 'a"b\\c\nd'
    with eng._lock:
        eng._queued_by_tenant[evil] = 3      # what a hostile tenant field
                                             # would poison if unescaped
    text = render_metrics(eng)
    # one sample per line survives: the raw newline became a literal
    # backslash-n, the quote became backslash-quote
    assert 'heat_tpu_serve_queue_depth{tenant="a\\"b\\\\c\\nd"} 3' in text
    assert 'a"b' not in text and "\nd\"}" not in text


def test_tracez_endpoint_and_x_trace_id_header(tmp_path):
    """GET /tracez returns the live engine's ring as loadable Chrome
    trace JSON; every solve response echoes the minted trace ids in
    X-Trace-Id and every NDJSON record carries its trace_id."""
    gw, eng = make_gateway(tmp_path)
    try:
        st, recs, hdrs = http(gw, "POST", "/v1/solve",
                              line(id="t1", n=16, ntime=8,
                                   dtype="float64"))
        assert st == 200 and recs[0]["status"] == "ok"
        assert recs[0]["trace_id"]
        assert hdrs.get("X-Trace-Id") == recs[0]["trace_id"]
        st, (rec,), hdrs = http(gw, "GET", "/v1/requests/t1")
        assert hdrs.get("X-Trace-Id") == rec["trace_id"]
        resp = urllib.request.urlopen(f"http://{gw.address}/tracez",
                                      timeout=TIMEOUT)
        assert resp.status == 200
        assert resp.headers["Content-Type"].startswith("application/json")
        obj = json.loads(resp.read().decode())
        evs = obj["traceEvents"]
        assert any(e.get("name") == "t1" and e["ph"] == "X" for e in evs)
        assert any(e.get("name") == "POST /v1/solve" for e in evs)
        assert any(e.get("args", {}).get("trace_id") == rec["trace_id"]
                   for e in evs)
    finally:
        gw.request_drain()
        assert gw.wait_drained(TIMEOUT)
        gw.close()


# --- CLI gateway mode --------------------------------------------------------


def test_serve_cli_listen_mode_end_to_end(tmp_cwd, capsys, monkeypatch):
    """`heat-tpu serve --listen` runs until /drainz completes: drive a
    whole session (pre-loaded JSONL file + one HTTP admission + drain)
    through cli.main on a background thread."""
    import heat_tpu.serve.gateway as gateway_mod
    from heat_tpu.cli import main

    (tmp_cwd / "reqs.jsonl").write_text(
        '{"id": "preload", "n": 16, "ntime": 12, "dtype": "float64"}\n')
    holder = {}
    real_start = gateway_mod.Gateway.start

    def capture_start(self):
        holder["gw"] = self
        return real_start(self)

    monkeypatch.setattr(gateway_mod.Gateway, "start", capture_start)
    rc = {}

    def run_cli():
        rc["rc"] = main(["serve", "--listen", "127.0.0.1:0",
                         "--requests", "reqs.jsonl", "--buckets", "16",
                         "--chunk", "8", "--policy", "edf"])

    t = threading.Thread(target=run_cli)
    t.start()
    try:
        deadline = TIMEOUT
        while "gw" not in holder and deadline > 0:
            import time

            time.sleep(0.05)
            deadline -= 0.05
        gw = holder["gw"]
        st, recs, _ = http(gw, "POST", "/v1/solve",
                           line(id="net", n=16, ntime=8, dtype="float64"))
        assert st == 200 and recs[0]["status"] == "ok"
        st, _, _ = http(gw, "POST", "/drainz")
        assert st == 200
    finally:
        t.join(TIMEOUT)
        assert not t.is_alive()
    assert rc["rc"] == 0
    out = capsys.readouterr().out
    assert "gateway listening on http://127.0.0.1:" in out
    assert "served 2 request(s): 2 ok" in out
    assert "policy edf" in out


# --- X-Trace-Id on EVERY response (ISSUE 8 satellite audit) -------------------


def test_x_trace_id_on_every_error_path_and_drainz():
    """The header contract is universal: 400, 404, 429, 503, /drainz,
    /metrics — every response names a trace id, and a sane inbound id is
    echoed back even on rejection paths."""
    gw, eng = make_gateway(max_queue=1, start_engine=False)
    try:
        # 400 empty body
        st, _, hdrs = http(gw, "POST", "/v1/solve", "")
        assert st == 400 and hdrs.get("X-Trace-Id")
        # 404 unknown route + unknown id
        st, _, hdrs = http(gw, "GET", "/no/such/route")
        assert st == 404 and hdrs.get("X-Trace-Id")
        st, _, hdrs = http(gw, "GET", "/v1/requests/nope")
        assert st == 404 and hdrs.get("X-Trace-Id")
        # 429 overloaded (held scheduler makes the bound deterministic):
        # the satellite's regression case — a REJECTED request still
        # carries the header (here: the submitted line's minted ids)
        st, _, _ = http(gw, "POST", "/v1/solve?wait=0",
                        line(id="q1", n=16, ntime=8, dtype="float64"))
        assert st == 202
        st, (body,), hdrs = http(gw, "POST", "/v1/solve?wait=0",
                                 line(id="q2", n=16, ntime=8,
                                      dtype="float64"))
        assert st == 429 and hdrs.get("X-Trace-Id")
        assert body["records"][0]["status"] == "rejected"
        # GET endpoints carry it too (raw fetch: /metrics and /statusz
        # bodies are text, not JSON lines)
        for path in ("/metrics", "/healthz", "/statusz", "/v1/usage",
                     "/tracez"):
            resp = urllib.request.urlopen(
                f"http://{gw.address}{path}", timeout=TIMEOUT)
            assert resp.headers.get("X-Trace-Id"), path
        # inbound echo: a sane client id survives the round trip on an
        # error path; junk is replaced with a minted id
        import urllib.request as _rq

        req = _rq.Request(f"http://{gw.address}/v1/requests/nope",
                          headers={"X-Trace-Id": "client-abc.123"})
        try:
            _rq.urlopen(req, timeout=TIMEOUT)
        except urllib.error.HTTPError as e:
            assert e.code == 404
            assert e.headers.get("X-Trace-Id") == "client-abc.123"
        req = _rq.Request(f"http://{gw.address}/healthz",
                          headers={"X-Trace-Id": "bad id\twith junk"})
        resp = _rq.urlopen(req, timeout=TIMEOUT)
        assert resp.headers.get("X-Trace-Id") != "bad id\twith junk"
        assert resp.headers.get("X-Trace-Id")
        # /drainz (the drain trigger itself is traceable)
        eng.start()
        st, _, hdrs = http(gw, "POST", "/drainz")
        assert st == 200 and hdrs.get("X-Trace-Id")
        # 503 while draining
        st, _, hdrs = http(gw, "POST", "/v1/solve",
                           line(n=16, ntime=4, dtype="float64"))
        assert st == 503 and hdrs.get("X-Trace-Id")
    finally:
        gw.request_drain()
        assert gw.wait_drained(TIMEOUT)
        gw.close()


# --- /statusz + /v1/usage over HTTP ------------------------------------------


def test_statusz_and_usage_endpoints_over_http(tmp_path):
    """GET /v1/usage totals reconcile exactly with the usage stamps on
    the records the same gateway streamed back (acceptance), and
    /statusz renders the operator snapshot."""
    gw, eng = make_gateway(tmp_path)
    try:
        st, recs, _ = http(
            gw, "POST", "/v1/solve",
            line(id="u1", n=16, ntime=16, dtype="float64",
                 tenant="acme", deadline_ms=60000)
            + line(id="u2", n=16, ntime=24, dtype="float64",
                   tenant="zeta", **{"class": "batch"}))
        assert st == 200
        assert all(r["status"] == "ok" for r in recs)
        assert all("usage" in r for r in recs)
        st, (payload,), _ = http(gw, "GET", "/v1/usage")
        assert st == 200
        tot = payload["totals"]
        assert tot["requests"] == 2
        assert tot["steps"] == sum(r["usage"]["steps"] for r in recs)
        assert tot["chunks"] == sum(r["usage"]["chunks"] for r in recs)
        assert tot["bytes_written"] == sum(
            r["usage"]["bytes_written"] for r in recs)
        assert abs(tot["lane_s"] - sum(r["usage"]["lane_s"]
                                       for r in recs)) < 1e-6
        assert set(payload["tenants"]) == {"acme", "zeta"}
        resp = urllib.request.urlopen(
            f"http://{gw.address}/statusz", timeout=TIMEOUT)
        assert resp.status == 200
        page = resp.read().decode()
        assert "cost model" in page and "usage ledger" in page
        assert "acme" in page and "slo burn" in page
        # the CLI's URL spelling fetches the same ledger
        import contextlib
        import io

        from heat_tpu.cli import main

        buf = io.StringIO()
        with contextlib.redirect_stdout(buf):
            rc = main(["usage", f"http://{gw.address}"])
        assert rc == 0
        out = buf.getvalue()
        assert "acme" in out and "TOTAL" in out
    finally:
        gw.request_drain()
        assert gw.wait_drained(TIMEOUT)
        gw.close()


# --- concurrent scrapes vs submits vs drain (ISSUE 8 satellite) --------------


def test_metrics_tracez_scraped_concurrently_with_submits_and_drain(
        tmp_path):
    """Lock-ordering/consistency regression: /metrics, /tracez, /statusz
    and /v1/usage hammered from scrape threads WHILE submit threads feed
    the running engine, and again mid-drain — every scrape answers 200
    with parseable content, nothing deadlocks, and the drain completes.
    (Counters are read under the engine lock from gateway threads; the
    observatory reads take only its own locks — engine->prof order.)"""
    gw, eng = make_gateway(tmp_path)
    stop = threading.Event()
    errors = []
    scrapes = {"n": 0}

    def scraper():
        import urllib.request as _rq

        while not stop.is_set():
            for path in ("/metrics", "/tracez", "/statusz", "/v1/usage"):
                try:
                    resp = _rq.urlopen(
                        f"http://{gw.address}{path}", timeout=TIMEOUT)
                    raw = resp.read().decode()
                    assert resp.status == 200
                    if path in ("/tracez", "/v1/usage"):
                        json.loads(raw)
                    elif path == "/metrics":
                        assert "heat_tpu_serve_requests_total" in raw
                    scrapes["n"] += 1
                except Exception as e:  # noqa: BLE001 — surfaced below
                    errors.append(f"{path}: {type(e).__name__}: {e}")
                    return

    def submitter(base):
        try:
            for i in range(3):
                st, recs, _ = http(
                    gw, "POST", "/v1/solve",
                    line(id=f"{base}-{i}", n=16, ntime=24,
                         dtype="float64", tenant=base))
                assert st == 200 and recs[0]["status"] == "ok"
        except Exception as e:  # noqa: BLE001 — surfaced below
            errors.append(f"submit {base}: {type(e).__name__}: {e}")

    scrapers = [threading.Thread(target=scraper) for _ in range(2)]
    submitters = [threading.Thread(target=submitter, args=(t,))
                  for t in ("acme", "zeta")]
    for t in scrapers + submitters:
        t.start()
    try:
        for t in submitters:
            t.join(TIMEOUT)
            assert not t.is_alive()
        # drain WHILE the scrapers keep hammering: the mid-drain scrape
        # is the regression case (draining flag + engine lock + ring
        # export all read from gateway threads)
        st, _, _ = http(gw, "POST", "/drainz")
        assert st == 200
        assert gw.wait_drained(TIMEOUT)
        resp = urllib.request.urlopen(
            f"http://{gw.address}/metrics", timeout=TIMEOUT)
        assert resp.status == 200
        assert "heat_tpu_serve_draining 1" in resp.read().decode()
    finally:
        stop.set()
        for t in scrapers:
            t.join(TIMEOUT)
            assert not t.is_alive()
        gw.close()
    assert not errors, errors
    assert scrapes["n"] > 0
    assert eng.summary()["ok"] == 6
