"""Multi-host deployment paths, faked on one host.

A real multi-host job can't run in CI; what CAN be tested is every branch
the multi-host world selects: the CLI's world-join call (the reference's
``mpi_init`` as the first act of ``program heat``,
fortran/mpi+cuda/heat.F90:60-70), the per-shard solution dump (per-rank
``soln#####.dat``, :277-288), the per-process shard checkpoints, and the
shard-checkpoint resume — all driven by faking "this array spans other
processes" at the single injectable seam (``backends.common._addressable``).
"""

import numpy as np
import pytest

import heat_tpu.backends.common as common
from heat_tpu.backends import solve
from heat_tpu.cli import main
from heat_tpu.config import HeatConfig
from heat_tpu.io import read_dat


@pytest.fixture
def fake_multihost(monkeypatch):
    """Every jax.Array now claims to span other processes."""
    monkeypatch.setattr(common, "_addressable", lambda x: False)


@pytest.fixture
def input_dat(tmp_cwd):
    (tmp_cwd / "input.dat").write_text("32 0.25 0.05 2.0 4 1\n")
    return tmp_cwd


def test_cli_sharded_calls_init_distributed(input_dat, monkeypatch):
    """cmd_run must join the world before any backend use — VERDICT r1: the
    flagship deployment story was dead code from the CLI."""
    calls = []
    import heat_tpu.parallel.dist as dist

    monkeypatch.setattr(dist, "init_distributed",
                        lambda *a, **k: calls.append(1))
    rc = main(["run", "--backend", "sharded", "--dtype", "float64",
               "--mesh", "2x2"])
    assert rc == 0
    assert calls == [1]


def test_cli_serial_skips_init_distributed(input_dat, monkeypatch):
    calls = []
    import heat_tpu.parallel.dist as dist

    monkeypatch.setattr(dist, "init_distributed",
                        lambda *a, **k: calls.append(1))
    assert main(["run", "--backend", "serial", "--dtype", "float64"]) == 0
    assert calls == []


def test_host_fetch_returns_none_for_remote_arrays(fake_multihost):
    import jax.numpy as jnp

    assert common.host_fetch(jnp.ones((4, 4))) is None


def test_host_fetch_passes_addressable_arrays():
    import jax.numpy as jnp

    assert common.host_fetch(np.ones(3)) is not None
    np.testing.assert_array_equal(common.host_fetch(jnp.ones((2, 2))),
                                  np.ones((2, 2)))


def test_solve_multihost_skips_global_fetch(fake_multihost):
    cfg = HeatConfig(n=16, ntime=2, dtype="float32", backend="sharded",
                     mesh_shape=(2, 2))
    res = solve(cfg)
    assert res.T is None           # global gather would raise on a real pod
    assert res.T_dev is not None   # device handle kept for per-shard IO
    assert res.mesh is not None


def test_cli_multihost_soln_routes_through_per_shard_writer(
        input_dat, fake_multihost, capsys):
    rc = main(["run", "--backend", "sharded", "--dtype", "float64",
               "--mesh", "2x2"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "shard files" in out
    assert not (input_dat / "soln.dat").exists()  # no global gather
    shard_files = sorted(input_dat.glob("soln0*.dat"))
    assert len(shard_files) == 4
    # shards reassemble to the serial oracle
    ref = solve(HeatConfig(n=32, ntime=4, dtype="float64", backend="serial"))
    _, blk0 = read_dat(shard_files[0])
    np.testing.assert_allclose(blk0, ref.T[:16, :16], atol=1e-6)


def test_multihost_checkpoints_per_process_shards(tmp_cwd, fake_multihost):
    cfg = HeatConfig(n=16, ntime=4, dtype="float32", backend="sharded",
                     mesh_shape=(2, 2), checkpoint_every=2,
                     checkpoint_dir=str(tmp_cwd / "ck"))
    solve(cfg)
    files = sorted((tmp_cwd / "ck").glob("heat_shards_step*.npz"))
    assert [f.name for f in files] == [
        "heat_shards_step00000002.proc0000.npz",
        "heat_shards_step00000004.proc0000.npz",
    ]
    # the global-checkpoint glob must NOT pick these up
    from heat_tpu.runtime import checkpoint

    assert checkpoint.latest(cfg) is None


def test_multihost_resume_from_shard_checkpoints(tmp_cwd, fake_multihost):
    ckdir = str(tmp_cwd / "ck")
    cfg = HeatConfig(n=16, ntime=4, dtype="float32", backend="sharded",
                     mesh_shape=(2, 2), checkpoint_every=2,
                     checkpoint_dir=ckdir)
    solve(cfg)  # leaves shard checkpoints at steps 2 and 4

    # extend the run; it must resume from the step-4 shard files
    cfg2 = cfg.with_(ntime=6)
    res = solve(cfg2)
    assert res.start_step == 4
    # and match an uninterrupted 6-step run bit-for-bit
    clean = solve(cfg2.with_(checkpoint_every=0))
    np.testing.assert_array_equal(np.asarray(res.T_dev),
                                  np.asarray(clean.T_dev))


def test_shard_checkpoint_fingerprint_mismatch_rejected(tmp_cwd, fake_multihost):
    ckdir = str(tmp_cwd / "ck")
    cfg = HeatConfig(n=16, ntime=2, dtype="float32", backend="sharded",
                     mesh_shape=(2, 2), checkpoint_every=2,
                     checkpoint_dir=ckdir)
    solve(cfg)
    from heat_tpu.runtime import checkpoint

    with pytest.raises(ValueError, match="different physics"):
        checkpoint.load_shards(cfg.with_(nu=0.99), 2)


def test_init_distributed_touches_no_backend():
    """jax.distributed.initialize raises once XLA backends exist, so the
    world-join no-op decision must not itself initialize a backend (r1's
    version called jax.process_count() first — dead on arrival on a pod).
    Needs a fresh interpreter: this test process already has backends up."""
    import subprocess
    import sys

    code = (
        "import jax\n"
        "from heat_tpu.parallel.dist import init_distributed\n"
        "init_distributed()\n"
        "from jax._src import xla_bridge\n"
        "assert not xla_bridge.backends_are_initialized(), "
        "'init_distributed initialized an XLA backend'\n"
        "print('clean')\n"
    )
    proc = subprocess.run([sys.executable, "-c", code], capture_output=True,
                          text=True, timeout=120,
                          env={**__import__('os').environ,
                               "JAX_PLATFORMS": "cpu"})
    assert proc.returncode == 0, proc.stderr
    assert "clean" in proc.stdout


def test_mapping_announcement_lines(input_dat, capsys):
    rc = main(["run", "--backend", "sharded", "--dtype", "float64",
               "--mesh", "2x2"])
    assert rc == 0
    out = capsys.readouterr().out
    # reference stdout contract: local dims (mpi+cuda/heat.F90:239-240) and
    # shard->device binding (:69)
    assert "local block: 16 x 16" in out
    assert "mesh (0, 0) -> device" in out
    assert "mesh (1, 1) -> device" in out


def test_check_finite_never_host_fetches_device_arrays(monkeypatch):
    """VERDICT r2 weak #4: check_finite used np.asarray(T) — on a global
    array spanning other processes that RAISES instead of checking. The
    fix reduces on device; this guard makes any host fetch of a jax.Array
    inside check_finite fail the way a real multi-host fetch would."""
    import jax
    import jax.numpy as jnp
    import numpy

    from heat_tpu.runtime.debug import check_finite

    real_asarray = numpy.asarray

    def guarded(x, *a, **k):
        if isinstance(x, jax.Array):
            raise RuntimeError(
                "Fetching value for jax.Array that spans non-addressable"
                " devices")
        return real_asarray(x, *a, **k)

    monkeypatch.setattr(numpy, "asarray", guarded)
    check_finite(jnp.ones((8, 8)), step=1)  # device-side path: no fetch
    with pytest.raises(FloatingPointError, match="step 2"):
        check_finite(jnp.full((4, 4), jnp.nan), step=2)
    # host arrays still take the numpy path
    check_finite(np.ones((4, 4)), step=3)


def test_solve_check_numerics_multihost(fake_multihost):
    """--check-numerics end to end in a (faked) multi-host world: drive
    calls check_finite on the global sharded array every chunk; the solve
    must complete without any global host fetch."""
    cfg = HeatConfig(n=16, ntime=4, dtype="float32", backend="sharded",
                     mesh_shape=(2, 2), check_numerics=True)
    res = solve(cfg)
    assert res.T is None           # global fetch correctly skipped
    assert res.T_dev is not None
