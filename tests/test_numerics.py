"""Numerics observatory (ISSUE 15): per-lane solution-quality telemetry
riding the widened boundary vector.

Contracts under test:

- the boundary vector is ``(K_BOUNDARY, L)`` int32 with rows 2-5 a
  bitcast float32 stats block — pack/unpack round-trip exactly,
  including non-finite payloads;
- always-compute, host-gate: ``--numerics off`` changes ZERO output
  bytes (in-memory and npz, XLA and Pallas, depths 0 and 2, f32/bf16,
  2D/3D) and adds ZERO device->host fetches — the stats ride a fetch
  that already happens;
- the observatory math (runtime/numerics.py): envelope tolerance,
  fire-once steady/violation latches, heat-jump arming, non-finite
  ingestion discipline;
- the e2e detector story: a seeded ``perturb`` fault fires ONE
  ``numerics_violation`` record + flight dump under guard=warn, and
  under guard=quarantine frees the lane with co-scheduled lanes
  byte-identical to a clean run;
- the analytic backbone: the sine IC decays by exactly
  ``sine_decay_factor(cfg)**s`` under frozen-edge FTCS (the prober's
  closed form).
"""

import json
import math
import pathlib

import numpy as np
import pytest

from heat_tpu.config import HeatConfig
from heat_tpu.grid import ic_envelope, initial_condition, sine_decay_factor
from heat_tpu.runtime import faults
from heat_tpu.runtime import numerics as numerics_mod
from heat_tpu.serve import Engine, ServeConfig
from heat_tpu.serve import engine as engine_mod
from heat_tpu.serve.engine import BOUNDARY_ROWS, K_BOUNDARY, unpack_boundary


@pytest.fixture(autouse=True)
def _fresh_faults():
    faults.reset()
    yield
    faults.reset()


def quiet(**kw) -> ServeConfig:
    kw.setdefault("emit_records", False)
    kw.setdefault("keep_fields", True)
    return ServeConfig(**kw)


def drain(reqs, **kw):
    """Drain ``reqs`` through one engine; records in submit order."""
    eng = Engine(quiet(**kw))
    ids = [eng.submit(cfg) for cfg in reqs]
    by_id = {r["id"]: r for r in eng.results()}
    return eng, [by_id[i] for i in ids]


def records_of(capsys, event):
    out = capsys.readouterr().out
    return [json.loads(line) for line in out.splitlines()
            if line.startswith("{")
            and json.loads(line).get("event") == event]


# --- the widened boundary vector ---------------------------------------------


def test_boundary_layout_is_the_widened_contract():
    assert BOUNDARY_ROWS == ("remaining", "finite", "resid", "tmin",
                             "tmax", "heat")
    assert K_BOUNDARY == 6


def test_pack_unpack_boundary_roundtrip_bitexact():
    """Rows 0-1 stay plain int32; the f32 stats block survives the int32
    bitcast bit-for-bit — including NaN/Inf payloads, which a value cast
    would mangle."""
    import jax.numpy as jnp

    from heat_tpu.serve.engine import pack_boundary

    rem = jnp.asarray([7, 0, 3], jnp.int32)
    fin = jnp.asarray([1, 1, 0], jnp.int32)
    stats = np.asarray(
        [[1e-3, 0.0, np.nan],
         [-1.5, 2.0, -np.inf],
         [2.5, 2.0, np.inf],
         [123.25, 0.0, 7.5]], dtype=np.float32)
    b = np.asarray(pack_boundary(rem, fin, jnp.asarray(stats)))
    assert b.shape == (K_BOUNDARY, 3) and b.dtype == np.int32
    np.testing.assert_array_equal(b[0], [7, 0, 3])
    np.testing.assert_array_equal(b[1], [1, 1, 0])
    back = unpack_boundary(b)
    assert back.dtype == np.float32 and back.shape == (4, 3)
    assert back.tobytes() == stats.tobytes()   # NaN payloads included


# --- always-compute, host-gate: on vs off is byte-identical ------------------
#
# Not a full cross-product (each Pallas cell compiles interpret-mode
# programs); the cells collectively cover {xla, pallas} x {f32, bf16}
# x {2D, 3D} with dispatch depths {0, 2} distributed across them.

MATRIX = [
    # (kernel, ndim, dtype, depth)
    ("xla", 2, "float32", 0),
    ("xla", 3, "float32", 2),
    ("xla", 2, "bfloat16", 2),
    ("xla", 3, "bfloat16", 0),
    ("pallas", 2, "float32", 2),
    ("pallas", 3, "float32", 0),
    ("pallas", 2, "bfloat16", 0),
    ("pallas", 3, "bfloat16", 2),
]


def matrix_requests(ndim, dtype):
    small = 6 if ndim == 3 else 8
    big = 8 if ndim == 3 else 12
    return [
        HeatConfig(n=big, ntime=13, ndim=ndim, dtype=dtype, bc="ghost",
                   ic="hat"),
        HeatConfig(n=small, ntime=21, ndim=ndim, dtype=dtype, bc="edges",
                   ic="uniform", nu=0.1),
        HeatConfig(n=big - 2, ntime=9, ndim=ndim, dtype=dtype, bc="edges",
                   ic="sine"),
    ]


@pytest.mark.parametrize("kernel,ndim,dtype,depth", MATRIX)
def test_numerics_on_vs_off_byte_identical(kernel, ndim, dtype, depth):
    """The tentpole's acceptance spelling: the observatory is pure
    observation — toggling it changes no output byte on either chunk
    body (the stats rows are always computed; only host INGESTION is
    gated)."""
    reqs = matrix_requests(ndim, dtype)
    kw = dict(lanes=2, chunk=4, buckets=(8 if ndim == 3 else 12,),
              dispatch_depth=depth, lane_kernel=kernel)
    eng_on, recs_on = drain(reqs, numerics=True, **kw)
    eng_off, recs_off = drain(reqs, numerics=False, **kw)
    assert eng_on.numerics is not None and eng_off.numerics is None
    assert all(r["status"] == "ok" for r in recs_on)
    for a, b in zip(recs_on, recs_off):
        assert a["status"] == b["status"]
        assert a["T"].dtype == b["T"].dtype
        assert a["T"].tobytes() == b["T"].tobytes(), a["id"]


@pytest.mark.parametrize("kernel", ["xla", "pallas"])
def test_numerics_npz_outputs_byte_identical_across_depths(tmp_path, kernel):
    """Published npz artifacts from --numerics on and off are
    byte-identical at dispatch depths 0 AND 2 (the acceptance cells)."""
    reqs = matrix_requests(2, "float32")
    for depth in (0, 2):
        outs = {}
        for mode in (True, False):
            d = tmp_path / f"{kernel}-{depth}-{'on' if mode else 'off'}"
            drain(reqs, numerics=mode, lanes=2, chunk=4, buckets=(12,),
                  dispatch_depth=depth, lane_kernel=kernel,
                  keep_fields=False, out_dir=str(d))
            outs[mode] = d
        files = sorted(p.name for p in outs[True].glob("*.npz"))
        assert files   # the runs actually published
        for name in files:
            assert ((outs[True] / name).read_bytes()
                    == (outs[False] / name).read_bytes()), (name, depth)


def test_numerics_adds_no_boundary_fetches(monkeypatch):
    """Zero extra transfers: the stats ride the ONE boundary fetch that
    already happens, so the host_fetch count is identical on vs off."""
    real = engine_mod.host_fetch
    counts = {"n": 0}

    def spy(x):
        counts["n"] += 1
        return real(x)

    monkeypatch.setattr(engine_mod, "host_fetch", spy)
    reqs = matrix_requests(2, "float32")
    kw = dict(lanes=2, chunk=4, buckets=(12,), dispatch_depth=2)
    per_mode = {}
    for mode in (True, False):
        counts["n"] = 0
        _, recs = drain(reqs, numerics=mode, **kw)
        assert all(r["status"] == "ok" for r in recs)
        per_mode[mode] = counts["n"]
    assert per_mode[True] == per_mode[False] > 0


# --- observatory math (runtime/numerics.py unit tests) -----------------------


def test_envelope_tolerance_is_dtype_and_scale_aware():
    obs = numerics_mod.NumericsObservatory(steady_tol=1e-12)
    obs.admit("r", lo=0.0, hi=2.0, dtype="bfloat16")
    # bf16 allowance: 5e-2 * scale 2 = 0.1 — a storage-rounding excursion
    # inside it is NOT a violation
    assert obs.observe("r", resid=0.1, tmin=0.0, tmax=2.05, heat=10.0,
                       remaining=5) == []
    (ev,) = obs.observe("r", resid=0.1, tmin=0.0, tmax=2.3, heat=10.0,
                        remaining=4)
    assert ev["kind"] == "violation" and ev["why"] == "max-principle"
    assert ev["tmax"] == 2.3 and ev["hi"] == 2.0


def test_violation_latches_once_per_request():
    obs = numerics_mod.NumericsObservatory(steady_tol=1e-12)
    obs.admit("r", lo=1.0, hi=2.0, dtype="float32")
    assert len(obs.observe("r", resid=0.1, tmin=0.5, tmax=2.0, heat=1.0,
                           remaining=9)) == 1
    # still out of envelope on the next boundary: latched, no re-fire
    assert obs.observe("r", resid=0.1, tmin=0.4, tmax=2.0, heat=1.0,
                       remaining=8) == []
    assert obs.violation_total == 1
    assert obs.snapshot()["lanes"]["r"]["violated"] is True


def test_heat_jump_armed_after_two_boundaries():
    obs = numerics_mod.NumericsObservatory(steady_tol=1e-30)
    obs.admit("r", lo=-1e9, hi=1e9, dtype="float32")  # envelope never fires

    def feed(heat):
        return obs.observe("r", resid=1.0, tmin=0.0, tmax=1.0, heat=heat,
                           remaining=99)

    assert feed(100.0) == []        # first boundary: no delta yet
    assert feed(99.5) == []         # second: delta known, EWMA unarmed
    assert feed(99.0) == []         # smooth decay: no alarm
    (ev,) = feed(60.0)              # |Δ| = 39 >> 50 * max(ewma ~0.5, floor)
    assert ev["kind"] == "violation" and ev["why"] == "heat-jump"
    assert ev["heat"] == 60.0 and ev["heat_prev"] == 99.0


def test_nonfinite_stats_and_unknown_lanes_are_ignored():
    obs = numerics_mod.NumericsObservatory(steady_tol=1e-12)
    assert obs.observe("ghost-of-a-request", 0.0, 0.0, 1.0, 1.0, 5) == []
    obs.admit("r", lo=0.0, hi=1.0, dtype="float32")
    # a NaN stat (the lane is headed to the nonfinite path anyway) must
    # not poison the EWMAs or trip a detector
    assert obs.observe("r", resid=float("nan"), tmin=0.0, tmax=99.0,
                       heat=1.0, remaining=5) == []
    snap = obs.snapshot()["lanes"]["r"]
    assert snap["boundaries"] == 0 and snap["resid_ewma"] is None


def test_steady_fires_once_and_only_with_steps_remaining():
    obs = numerics_mod.NumericsObservatory(steady_tol=1e-6)
    obs.admit("r", lo=0.0, hi=1.0, dtype="float32")
    (ev,) = obs.observe("r", resid=0.0, tmin=0.0, tmax=1.0, heat=1.0,
                        remaining=5)
    assert ev["kind"] == "steady" and ev["steady_tol"] == 1e-6
    # fire-once: still converged on later boundaries, no re-fire
    assert obs.observe("r", 0.0, 0.0, 1.0, 1.0, 4) == []
    assert obs.steady_total == 1
    # a lane converging exactly at its LAST boundary isn't "burning chip"
    obs.admit("s", lo=0.0, hi=1.0, dtype="float32")
    assert obs.observe("s", 0.0, 0.0, 1.0, 1.0, 0) == []


def test_forget_drops_detector_state():
    obs = numerics_mod.NumericsObservatory(steady_tol=1e-12)
    obs.admit("r", lo=0.0, hi=1.0, dtype="float32")
    obs.forget("r")
    assert obs.snapshot()["lanes"] == {}
    obs.forget("r")   # idempotent on any terminal path


def test_serve_config_validates_numerics_knobs():
    with pytest.raises(ValueError, match="steady_tol"):
        ServeConfig(steady_tol=0.0)
    with pytest.raises(ValueError, match="numerics_guard"):
        ServeConfig(numerics_guard="page-someone")
    assert ServeConfig(numerics_guard="quarantine").numerics_guard == \
        "quarantine"


# --- e2e detectors through the serving stack ---------------------------------


def test_steady_state_record_fires_once_per_request(capsys):
    """A uniform field under frozen edges is ALREADY converged: resid is
    exactly 0 from the first chunk, so each request earns exactly one
    steady_state record despite many more boundaries."""
    reqs = [HeatConfig(n=12, ntime=40, dtype="float32", bc="edges",
                       ic="uniform"),
            HeatConfig(n=12, ntime=32, dtype="float32", bc="edges",
                       ic="uniform", nu=0.1)]
    eng, recs = drain(reqs, lanes=2, chunk=4, buckets=(12,))
    assert all(r["status"] == "ok" for r in recs)
    rows = records_of(capsys, "steady_state")
    assert sorted(r["id"] for r in rows) == sorted(r["id"] for r in recs)
    for row in rows:
        assert row["resid"] == 0.0 and row["remaining"] > 0
        assert row["steady_tol"] == 1e-12 and "trace_id" in row
    assert eng.summary()["steady_lanes"] == 2


PERTURB_REQS = [
    HeatConfig(n=12, ntime=24, dtype="float32", bc="ghost"),
    HeatConfig(n=12, ntime=24, dtype="float32", bc="edges",
               ic="hat_small"),
]


def run_perturbed(guard, flight_dir=None):
    eng = Engine(quiet(lanes=2, chunk=4, buckets=(12,),
                       inject="perturb@6:req=bad:eps=100",
                       numerics_guard=guard,
                       flight_dir=flight_dir))
    for rid, cfg in zip(("bad", "clean"), PERTURB_REQS):
        eng.submit(cfg, request_id=rid)
    by_id = {r["id"]: r for r in eng.results()}
    return eng, by_id


def test_perturb_fires_numerics_violation_with_flight_dump(tmp_path,
                                                           capsys):
    """guard=warn: the finite perturbation escapes the maximum-principle
    envelope -> ONE numerics_violation record with concrete witnesses +
    a flight-recorder dump; the request still completes (warn observes,
    never guards)."""
    faults.reset()
    eng, by_id = run_perturbed("warn", flight_dir=str(tmp_path))
    assert by_id["bad"]["status"] == "ok"     # warn never fails a lane
    assert by_id["clean"]["status"] == "ok"
    out = capsys.readouterr().out
    rows = [json.loads(line) for line in out.splitlines()
            if line.startswith("{")]
    viol = [r for r in rows if r.get("event") == "numerics_violation"]
    assert len(viol) == 1                     # fire-once latch
    v = viol[0]
    assert v["id"] == "bad" and v["why"] == "max-principle"
    assert v["guard"] == "warn" and v["tmax"] > v["hi"] + v["tol"]
    assert v["trace_id"]
    dumps = [r for r in rows if r.get("event") == "flightrec"]
    assert len(dumps) == 1 and "numerics violation" in dumps[0]["reason"]
    dump_path = pathlib.Path(dumps[0]["path"])
    assert dump_path.parent == tmp_path and dump_path.exists()
    assert eng.summary()["numerics_violations"] == 1
    assert eng.lanes_quarantined == 0


def test_perturb_quarantine_frees_lane_coscheduled_unharmed(capsys):
    """guard=quarantine: the violated lane takes the PR-5 quarantine
    exit (structured nonfinite failure naming the numerics verdict);
    the co-scheduled lane's bytes match a clean run exactly."""
    faults.reset()
    eng, by_id = run_perturbed("quarantine")
    assert by_id["bad"]["status"] == "nonfinite"
    assert "numerics" in (by_id["bad"]["error"] or "")
    assert by_id["clean"]["status"] == "ok"
    assert eng.lanes_quarantined == 1
    assert eng.summary()["numerics_violations"] == 1
    (v,) = records_of(capsys, "numerics_violation")
    assert v["guard"] == "quarantine"
    faults.reset()
    _, clean = drain([PERTURB_REQS[1]], lanes=2, chunk=4, buckets=(12,))
    assert by_id["clean"]["T"].tobytes() == clean[0]["T"].tobytes()


def test_statusz_and_metrics_surface_numerics(capsys):
    from heat_tpu.serve.gateway import render_metrics, render_statusz

    eng, _ = drain([HeatConfig(n=12, ntime=8, dtype="float32")],
                   lanes=1, chunk=4, buckets=(12,))
    text = render_metrics(eng)
    assert 'heat_tpu_numerics_enabled{guard="warn"} 1' in text
    assert "heat_tpu_numerics_steady_total" in text
    assert "heat_tpu_numerics_violations_total" in text
    assert "numerics: guard warn" in render_statusz(eng)
    eng_off, _ = drain([HeatConfig(n=12, ntime=8, dtype="float32")],
                       lanes=1, chunk=4, buckets=(12,), numerics=False)
    assert 'heat_tpu_numerics_enabled{guard="warn"} 0' in \
        render_metrics(eng_off)
    assert "observatory OFF" in render_statusz(eng_off)


# --- the analytic backbone ---------------------------------------------------


@pytest.mark.parametrize("ndim,n,ntime", [(2, 16, 25), (3, 8, 12)])
def test_sine_eigenmode_decays_by_closed_form(ndim, n, ntime):
    """The prober's entire premise: under frozen-edge FTCS the sine IC
    is an exact eigenmode — step s equals lambda**s * T0 to f64
    rounding."""
    import jax.numpy as jnp

    from heat_tpu.ops.stencil import ftcs_step_edges, run_steps

    cfg = HeatConfig(n=n, ntime=ntime, ndim=ndim, dtype="float64",
                     ic="sine", bc="edges")
    T0 = initial_condition(cfg)
    lam = sine_decay_factor(cfg)
    assert 0.0 < lam < 1.0
    T = np.asarray(run_steps(jnp.asarray(T0), ntime,
                             lambda t: ftcs_step_edges(t, cfg.r)))
    np.testing.assert_allclose(T, lam ** ntime * T0, rtol=0.0, atol=1e-12)


def test_sine_decay_factor_closed_form_value():
    cfg = HeatConfig(n=64, ndim=2, dtype="float32", ic="sine", bc="edges")
    lam = 1.0 - 4.0 * 2 * float(cfg.r) * math.sin(
        math.pi / (2.0 * 63)) ** 2
    assert sine_decay_factor(cfg) == pytest.approx(lam, rel=0, abs=0)


def test_ic_envelope_covers_presets_and_ghost_ring():
    assert ic_envelope(HeatConfig(ic="uniform", bc="edges")) == (2.0, 2.0)
    assert ic_envelope(HeatConfig(ic="zero", bc="edges")) == (0.0, 0.0)
    assert ic_envelope(HeatConfig(ic="sine", bc="edges")) == (0.0, 1.0)
    assert ic_envelope(HeatConfig(ic="hat", bc="edges")) == (1.0, 2.0)
    # ghost BCs clamp the ring at bc_value: it joins the envelope
    assert ic_envelope(HeatConfig(ic="hat", bc="ghost",
                                  bc_value=2.5)) == (1.0, 2.5)
    assert ic_envelope(HeatConfig(ic="hat", bc="ghost",
                                  bc_value=0.5)) == (0.5, 2.0)


def test_envelope_bounds_every_ic_preset_field():
    """ic_envelope is analytic; it must actually bound the constructed
    field (the detector would false-positive otherwise)."""
    for ic in ("uniform", "zero", "sine", "hat", "hat_small", "hat_half"):
        for bc in ("edges", "ghost"):
            cfg = HeatConfig(n=12, ndim=2, dtype="float64", ic=ic, bc=bc,
                             bc_value=1.5)
            lo, hi = ic_envelope(cfg)
            T0 = initial_condition(cfg)
            assert lo <= float(T0.min()) and float(T0.max()) <= hi, (ic, bc)
