"""Distributed correctness on the 8-device fake CPU mesh: decomposed runs
must match the undecomposed oracle (golden test, SURVEY.md §4 item 4)."""

import jax
import numpy as np
import pytest

from heat_tpu.backends import solve
from heat_tpu.config import HeatConfig
from heat_tpu.parallel.mesh import auto_mesh_shape, build_mesh, validate_divisible


BASE = HeatConfig(n=32, ntime=12, dtype="float64", backend="sharded")


def test_eight_devices_available():
    assert len(jax.devices()) >= 8, "conftest must provide 8 virtual CPU devices"


def test_auto_mesh_shape():
    assert auto_mesh_shape(8, 2) == (4, 2)
    assert auto_mesh_shape(16, 2) == (4, 4)   # BASELINE.json config 3
    assert auto_mesh_shape(8, 3) == (2, 2, 2)
    assert auto_mesh_shape(1, 2) == (1, 1)
    assert auto_mesh_shape(6, 2) == (3, 2)


def test_validate_divisible():
    mesh = build_mesh(2, (4, 2))
    validate_divisible(32, mesh)
    with pytest.raises(ValueError):
        validate_divisible(30, mesh)  # 30 % 4 != 0 -> loud failure


@pytest.mark.parametrize("mesh_shape", [(8, 1), (1, 8), (2, 4), (4, 2)])
def test_sharded_matches_serial_edges(mesh_shape):
    """2-D decomposition generalizing the reference's 1-D split
    (fortran/mpi+cuda/heat.F90:87-93); (8,1) IS the reference layout."""
    cfg = BASE.with_(mesh_shape=mesh_shape, bc="edges", ic="hat")
    expect = solve(cfg.with_(backend="serial"))
    got = solve(cfg)
    np.testing.assert_allclose(got.T, expect.T, rtol=0, atol=0)


@pytest.mark.parametrize("mesh_shape", [(8, 1), (2, 4)])
def test_sharded_matches_serial_ghost(mesh_shape):
    cfg = BASE.with_(mesh_shape=mesh_shape, bc="ghost", ic="uniform")
    expect = solve(cfg.with_(backend="serial"))
    got = solve(cfg)
    np.testing.assert_allclose(got.T, expect.T, rtol=0, atol=0)


@pytest.mark.parametrize("bc,ic", [("edges", "hat"), ("ghost", "uniform")])
def test_fused_halo_matches_per_step_exchange(bc, ic):
    """Communication-avoiding wide-halo steps == every-step exchange, exactly."""
    cfg = BASE.with_(mesh_shape=(2, 4), bc=bc, ic=ic, ntime=11)
    per_step = solve(cfg.with_(fuse_steps=1))
    fused = solve(cfg.with_(fuse_steps=4))  # 2 fused exchanges + remainder 3
    np.testing.assert_allclose(fused.T, per_step.T, rtol=0, atol=0)
    serial = solve(cfg.with_(backend="serial"))
    np.testing.assert_allclose(fused.T, serial.T, rtol=0, atol=0)


def test_fuse_depth_capped_by_local_extent():
    from heat_tpu.backends.sharded import fuse_depth_sharded

    cfg = BASE.with_(fuse_steps=0)          # auto: k* = sqrt(L/d)
    assert fuse_depth_sharded(cfg, (8, 1)) == round((4 / 2) ** 0.5)
    assert fuse_depth_sharded(cfg, (2, 2)) == round((16 / 2) ** 0.5)
    assert fuse_depth_sharded(cfg.with_(fuse_steps=3), (2, 2)) == 3
    # large local blocks clamp at the kernel's PER-PASS chunk depth (16
    # at flagship width under _thin_chunk_cap): the round-5 on-chip curve
    # measured k=16 12% faster than k=32 once k=32 executes as two
    # 16-deep passes (collective_overhead.json 2026-08-01)
    big = cfg.with_(n=16384, dtype="float32")
    assert fuse_depth_sharded(big, (1, 1)) == 16
    # ... while the xla local kernel (no per-pass chunk) keeps sqrt form
    assert fuse_depth_sharded(big.with_(local_kernel="xla"), (1, 1)) == 32
    # ... as does f64 (BASE's dtype), which always resolves to xla
    assert fuse_depth_sharded(big.with_(dtype="float64"), (1, 1)) == 32
    # and an explicit 32 is still honored
    assert fuse_depth_sharded(big.with_(fuse_steps=32), (1, 1)) == 32
    # near the band threshold the cap must be judged at the GHOST-PADDED
    # width the kernel actually sees: local 4864 reads 32 unpadded but
    # the (4864+2*32)-wide runtime band crosses the cap -> 16 (review r5)
    near = cfg.with_(n=4864, dtype="float32")
    assert fuse_depth_sharded(near, (1, 1)) == 16


def test_sharded_staged_comm_matches_direct():
    """NO_AWARE staged path == CUDA-aware path numerically
    (fortran/mpi+cuda/heat.F90:162-172: same data, different route)."""
    cfg = BASE.with_(mesh_shape=(2, 2), bc="ghost", ic="uniform", ntime=6)
    direct = solve(cfg.with_(comm="direct"))
    staged = solve(cfg.with_(comm="staged"))
    np.testing.assert_allclose(staged.T, direct.T, rtol=0, atol=0)


def test_staged_comm_host_roundtrip_actually_fires(monkeypatch):
    """comm='staged' must really route slabs through host memory — numeric
    equality alone is tautological (the callback is an identity). Count the
    host callbacks: staged fires them per axis per exchange (send + recv
    legs, distinct slab shapes per axis); direct fires none. Fails if
    staged=True silently takes the direct path."""
    import jax as _jax

    from heat_tpu.parallel import halo

    calls = []

    def counting_stage(x):
        def cb(a):
            calls.append(tuple(a.shape))
            return np.asarray(a)

        return _jax.pure_callback(
            cb, _jax.ShapeDtypeStruct(x.shape, x.dtype), x,
            vmap_method="sequential")

    monkeypatch.setattr(halo, "_stage_through_host", counting_stage)
    cfg = BASE.with_(mesh_shape=(2, 2), bc="ghost", ic="uniform", ntime=4,
                     fuse_steps=2)
    solve(cfg.with_(comm="staged"))
    n_exchanges = 2  # ntime=4 at fuse depth 2
    # send+recv legs on both sides of both axes: >= 4 stagings per axis per
    # exchange (per shard on top, but don't over-specify runtime sharding)
    assert len(calls) >= 4 * 2 * n_exchanges, calls
    shapes = set(calls)
    w = 2  # fused halo width
    assert any(s[0] == w and s[1] > w for s in shapes), shapes  # x-axis slabs
    assert any(s[1] == w and s[0] > w for s in shapes), shapes  # y-axis slabs

    calls.clear()
    solve(cfg.with_(comm="direct"))
    assert calls == [], "direct path must never stage through host"


def test_sharded_3d():
    cfg = HeatConfig(n=16, ndim=3, ntime=5, dtype="float64", sigma=0.15,
                     ic="hat", backend="sharded", mesh_shape=(2, 2, 2))
    expect = solve(cfg.with_(backend="serial"))
    got = solve(cfg)
    # 7-point sum reassociation: ~1 ulp
    np.testing.assert_allclose(got.T, expect.T, rtol=0, atol=1e-14)


def test_sharded_report_sum():
    cfg = BASE.with_(mesh_shape=(4, 2), bc="ghost", ic="uniform",
                     report_sum=True)
    expect = solve(cfg.with_(backend="serial"))
    got = solve(cfg)
    assert got.gsum == pytest.approx(expect.gsum, rel=1e-12)


def test_sharded_f32_and_bf16():
    for dt, atol in [("float32", 1e-6), ("bfloat16", 3e-2)]:
        cfg = BASE.with_(mesh_shape=(2, 4), dtype=dt, ntime=8)
        expect = solve(cfg.with_(backend="serial", dtype="float32"))
        got = solve(cfg)
        np.testing.assert_allclose(
            np.asarray(got.T, np.float32), expect.T, rtol=0, atol=atol
        )


def test_mesh_too_large_rejected():
    with pytest.raises(ValueError):
        build_mesh(2, (16, 16))


@pytest.mark.parametrize("bc", ["edges", "ghost"])
@pytest.mark.parametrize("mesh_shape", [(4, 2), (1, 1)])
def test_sharded_pallas_local_kernel_matches_serial(bc, mesh_shape):
    """The per-shard Pallas fast path (bounded frozen region, interpret mode
    on CPU) must match the serial oracle like the XLA path does."""
    cfg = BASE.with_(mesh_shape=mesh_shape, bc=bc, ic="hat", dtype="float32",
                     local_kernel="pallas", fuse_steps=3)
    expect = solve(cfg.with_(backend="serial", mesh_shape=None,
                             local_kernel="auto"))
    got = solve(cfg)
    np.testing.assert_allclose(got.T, expect.T, rtol=0, atol=2e-6)


def test_sharded_pallas_matches_xla_local_kernel():
    cfg = BASE.with_(mesh_shape=(2, 4), bc="ghost", ic="uniform",
                     dtype="float32", fuse_steps=4)
    x = solve(cfg.with_(local_kernel="xla"))
    p = solve(cfg.with_(local_kernel="pallas"))
    np.testing.assert_allclose(p.T, x.T, rtol=0, atol=2e-6)


def test_sharded_pallas_3d_matches_serial():
    cfg = HeatConfig(n=16, ndim=3, ntime=6, dtype="float32", sigma=1 / 6,
                     backend="sharded", mesh_shape=(2, 2, 2), bc="ghost",
                     ic="hat", local_kernel="pallas", fuse_steps=2)
    expect = solve(cfg.with_(backend="serial", mesh_shape=None))
    got = solve(cfg)
    np.testing.assert_allclose(got.T, expect.T, rtol=0, atol=2e-6)


def test_sharded_pallas_f64_rejected_loudly():
    cfg = BASE.with_(mesh_shape=(2, 2), local_kernel="pallas", dtype="float64")
    with pytest.raises(ValueError, match="local_kernel='pallas'"):
        solve(cfg)


def test_report_sum_survives_fetch_false():
    cfg = BASE.with_(mesh_shape=(2, 2), report_sum=True, dtype="float32")
    fetched = solve(cfg)
    nofetch = solve(cfg, fetch=False)
    assert nofetch.gsum is not None
    np.testing.assert_allclose(nofetch.gsum, fetched.gsum, rtol=1e-6)


def test_padded_carry_matches_owned_state_path():
    """The padded-carry fast path (default) and the owned-state path (used
    under checkpointing/numerics-checking) must agree bit-for-bit — same
    exchange, same kernel, same bounds; only the pad/crop placement moves."""
    for bc, ic in (("edges", "hat"), ("ghost", "uniform"),
                   ("periodic", "hat")):
        cfg = BASE.with_(mesh_shape=(2, 4), bc=bc, ic=ic, ntime=9)
        fast = solve(cfg)
        # check_numerics=True forces the owned-state path (and actually
        # checks finiteness along the way)
        classic = solve(cfg.with_(check_numerics=True))
        np.testing.assert_allclose(fast.T, classic.T, rtol=0, atol=0)

    # the Pallas branch of padded_multi too (interpret mode on CPU; f32)
    cfg = BASE.with_(mesh_shape=(2, 4), bc="ghost", ic="uniform", ntime=9,
                     dtype="float32", local_kernel="pallas")
    fast = solve(cfg)
    classic = solve(cfg.with_(check_numerics=True))
    np.testing.assert_allclose(fast.T, classic.T, rtol=0, atol=0)


def test_fuse_depth_rank_aware_caps():
    """VERDICT r2 weak #5: auto fuse depth must cap at the local kernel's
    per-pass chunk depth for the rank — 3D's kernel chunks at _KMAX_3D=8,
    so exchanging wider pays margin compute on three axes for marginal
    collective savings."""
    from heat_tpu.backends.sharded import fuse_depth_sharded
    from heat_tpu.ops.pallas_stencil import _KMAX_2D, _KMAX_3D

    cfg3 = HeatConfig(n=512, ndim=3, dtype="float32", backend="sharded")
    # sqrt(512/3) ~ 13 would exceed the 3D kernel's chunk depth of 8
    assert fuse_depth_sharded(cfg3, (1, 1, 1)) == _KMAX_3D
    assert fuse_depth_sharded(cfg3, (2, 2, 2)) <= _KMAX_3D
    # 2D clamps at the thin-band per-pass chunk depth at flagship width
    # (round-5 measured optimum; _KMAX_2D=32 remains the EXPLICIT cap)
    cfg2 = HeatConfig(n=16384, ndim=2, dtype="float32", backend="sharded")
    assert fuse_depth_sharded(cfg2, (1, 1)) == 16
    assert fuse_depth_sharded(cfg2.with_(fuse_steps=_KMAX_2D),
                              (1, 1)) == _KMAX_2D
    # explicit requests are honored (capped only by the local extent)
    assert fuse_depth_sharded(cfg3.with_(fuse_steps=16), (1, 1, 1)) == 16
    # tiny local extents still clamp
    assert fuse_depth_sharded(cfg3.with_(n=8), (4, 4, 4)) <= 2


def test_sharded_3d_auto_depth_matches_serial():
    """Pin the 3D auto-depth path end to end: auto fuse (now capped at 8)
    must still bit-match the serial oracle."""
    cfg = HeatConfig(n=16, ndim=3, ntime=10, dtype="float64",
                     backend="sharded", mesh_shape=(2, 2, 2))
    from heat_tpu.backends.sharded import fuse_depth_sharded

    assert 1 < fuse_depth_sharded(cfg, (2, 2, 2)) <= 8
    res = solve(cfg)
    ref = solve(cfg.with_(backend="serial", mesh_shape=None))
    np.testing.assert_array_equal(res.T, ref.T)


def test_build_mesh_cpu_keeps_plain_device_order():
    """Off-TPU, build_mesh is a plain reshape (deterministic shard->device
    binding for the virtual-device tests); the ICI-topology-aware ordering
    (mesh_utils) only engages on real multi-chip TPU."""
    import jax

    from heat_tpu.parallel.mesh import build_mesh

    mesh = build_mesh(2, (4, 2))
    assert [d.id for d in mesh.devices.flat] == [
        d.id for d in jax.devices()[:8]]


@pytest.mark.parametrize("periodic", [False, True])
@pytest.mark.parametrize("mesh_shape,width", [
    ((4, 2), 1), ((4, 2), 3), ((2, 4), 2), ((2, 2, 2), 2),
])
def test_halo_exchange_indep_bitwise(mesh_shape, width, periodic):
    """halo_exchange_indep must deliver bit-identical ghosts to the
    sequential formulation — including corner/edge regions, whose data
    the indep form forwards by stitching recv slabs instead of re-reading
    the updated array (parallel/halo.py)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    try:
        from jax import shard_map
    except ImportError:  # pragma: no cover
        from jax.experimental.shard_map import shard_map

    from heat_tpu.parallel.halo import halo_exchange, halo_exchange_indep

    ndim = len(mesh_shape)
    mesh = build_mesh(ndim, mesh_shape)
    rng = np.random.default_rng(17)
    n = 16
    w = width
    # per-shard padded arrays, random everywhere (ghosts hold garbage —
    # both formulations must overwrite every ghost cell they claim to)
    gshape = tuple(s * (n + 2 * w) for s in mesh_shape)
    G = rng.normal(size=gshape)
    spec = P(*mesh.axis_names)
    Gd = jax.device_put(G, NamedSharding(mesh, spec))

    outs = {}
    for name, fn in (("seq", halo_exchange), ("indep", halo_exchange_indep)):
        def body(local, fn=fn):
            return fn(local, mesh.axis_names, mesh_shape, 2.0,
                      width=w, periodic=periodic)

        outs[name] = np.asarray(
            jax.jit(shard_map(body, mesh=mesh, in_specs=(spec,),
                              out_specs=spec))(Gd))
    np.testing.assert_array_equal(outs["seq"], outs["indep"])


@pytest.mark.parametrize("mesh_shape,ndim", [((4, 2), 2), ((2, 2, 2), 3)])
def test_exchange_indep_solve_bitwise(mesh_shape, ndim):
    """End to end: exchange='indep' solves bit-identical to 'seq' at
    fused depth (corners matter there) on 2-D and 3-D meshes."""
    cfg = HeatConfig(n=24, ntime=10, ndim=ndim, dtype="float64",
                     backend="sharded", bc="ghost", fuse_steps=3,
                     mesh_shape=mesh_shape)
    a = solve(cfg.with_(exchange="seq")).T
    b = solve(cfg.with_(exchange="indep")).T
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
