"""Serving engine: batched-lane correctness, continuous batching, bucket
admission, per-request fault isolation, and the serve CLI.

The load-bearing contract is bit-identity: a request served through the
vmapped masked lanes must produce, on CPU at the same dtype, the exact
bytes a solo ``heat-tpu run`` of the same config produces — including
requests admitted mid-flight into a lane another request just vacated
(the continuous-batching path)."""

import json

import numpy as np
import pytest

from heat_tpu.backends import solve
from heat_tpu.config import HeatConfig, config_from_request
from heat_tpu.runtime import faults
from heat_tpu.serve import Engine, ServeConfig
from heat_tpu.serve import engine as engine_mod
from heat_tpu.serve.engine import (BucketKey, LaneEngine, lane_buffer,
                                   lane_tier, tail_size)


@pytest.fixture(autouse=True)
def _fresh_faults():
    faults.reset()
    yield
    faults.reset()


def quiet(**kw) -> ServeConfig:
    kw.setdefault("emit_records", False)
    return ServeConfig(**kw)


# --- batched-engine bit-identity -------------------------------------------


MIXED_REQUESTS = [
    # mixed sizes, BCs, diffusivities, step counts — all one engine run;
    # 6 requests over 2 lanes forces continuous-batching admission
    HeatConfig(n=17, ntime=37, dtype="float64", bc="edges", ic="hat"),
    HeatConfig(n=32, ntime=50, dtype="float64", bc="ghost", ic="uniform"),
    HeatConfig(n=24, ntime=5, dtype="float64", bc="edges", ic="hat_small",
               nu=0.1),
    HeatConfig(n=40, ntime=20, dtype="float64", bc="edges", ic="hat"),
    HeatConfig(n=20, ntime=64, dtype="float64", bc="ghost", ic="hat",
               bc_value=2.5),
    HeatConfig(n=17, ntime=3, dtype="float64", bc="ghost", ic="hat_half"),
]


def test_batched_lanes_bit_identical_to_solo_runs():
    """Acceptance: every lane's final field == the solo run of the same
    config, bitwise, including lanes filled mid-flight (6 requests, 2
    lanes -> 4 of them are continuous-batching admits)."""
    eng = Engine(quiet(lanes=2, chunk=8, buckets=(32, 48)))
    ids = [eng.submit(cfg) for cfg in MIXED_REQUESTS]
    recs = {r["id"]: r for r in eng.results()}
    for cfg, rid in zip(MIXED_REQUESTS, ids):
        rec = recs[rid]
        assert rec["status"] == "ok", rec
        solo = solve(cfg).T
        assert solo.dtype == rec["T"].dtype
        np.testing.assert_array_equal(rec["T"], solo)


def test_mid_flight_admit_into_freed_lane_is_exact():
    """One lane, three requests: requests 2 and 3 can ONLY run by being
    swapped into the lane request 1 finished in. Different step counts so
    the masked per-step countdown (not the chunk size) sets each stop."""
    cfgs = [HeatConfig(n=16, ntime=k, dtype="float64", bc="edges")
            for k in (7, 19, 30)]  # none a multiple of chunk=8
    eng = Engine(quiet(lanes=1, chunk=8, buckets=(16,)))
    ids = [eng.submit(c) for c in cfgs]
    recs = {r["id"]: r for r in eng.results()}
    for cfg, rid in zip(cfgs, ids):
        np.testing.assert_array_equal(recs[rid]["T"], solve(cfg).T)
    # one lane means one (bucket, lane-count) combo: exactly one compile
    assert eng.step_compiles == 1


def test_float32_and_bfloat16_lanes_match_solo():
    for dtype in ("float32", "bfloat16"):
        cfgs = [HeatConfig(n=12, ntime=9, dtype=dtype, bc="edges"),
                HeatConfig(n=16, ntime=14, dtype=dtype, bc="ghost",
                           ic="uniform")]
        eng = Engine(quiet(lanes=2, chunk=4, buckets=(16,)))
        ids = [eng.submit(c) for c in cfgs]
        recs = {r["id"]: r for r in eng.results()}
        for cfg, rid in zip(cfgs, ids):
            solo = solve(cfg).T
            assert np.array_equal(np.asarray(recs[rid]["T"], np.float32),
                                  np.asarray(solo, np.float32))


def test_3d_requests_served():
    cfg = HeatConfig(n=10, ntime=6, ndim=3, dtype="float64", bc="ghost",
                     ic="uniform", sigma=0.1)
    eng = Engine(quiet(lanes=2, chunk=4, buckets=(12,)))
    rid = eng.submit(cfg)
    rec = eng.results()[0]
    assert rec["status"] == "ok" and rec["id"] == rid
    np.testing.assert_array_equal(rec["T"], solve(cfg).T)


def test_zero_step_request_returns_ic():
    cfg = HeatConfig(n=8, ntime=0, dtype="float64")
    eng = Engine(quiet(lanes=1, chunk=4, buckets=(8,)))
    eng.submit(cfg)
    np.testing.assert_array_equal(eng.results()[0]["T"], solve(cfg).T)


def test_compile_count_one_per_bucket_lane_combo():
    """Acceptance: at most one stepping compile per (bucket, lane-count),
    however many requests flow through — and a second wave of submits
    reuses the warm programs (zero new compiles)."""
    cfgs = [HeatConfig(n=n, ntime=4, dtype="float64")
            for n in (8, 10, 12, 14, 16, 9, 11, 13)]
    eng = Engine(quiet(lanes=3, chunk=4, buckets=(12, 16)))
    for c in cfgs:
        eng.submit(c)
    eng.results()
    # two buckets, both with >= 3 requests -> exactly two combos
    assert eng.step_compiles == 2
    before = eng.step_compiles
    for c in cfgs:
        eng.submit(c)
    recs = eng.results()
    assert eng.step_compiles == before  # warm reuse across waves
    assert sum(r["status"] == "ok" for r in recs) == 2 * len(cfgs)


# --- dispatch-ahead (ISSUE 4) ----------------------------------------------


@pytest.mark.parametrize("depth", [0, 1, 2, 4])
def test_bit_identity_at_every_dispatch_depth(depth):
    """Acceptance: served results are bit-identical to solo runs at every
    dispatch depth — including depth=0 (the sync fallback) and depths
    deeper than the chunk count — with mid-flight admits (6+1 requests
    over 2 lanes) and a zero-step request in the mix."""
    cfgs = MIXED_REQUESTS + [HeatConfig(n=12, ntime=0, dtype="float64")]
    eng = Engine(quiet(lanes=2, chunk=8, buckets=(32, 48),
                       dispatch_depth=depth))
    ids = [eng.submit(cfg) for cfg in cfgs]
    recs = {r["id"]: r for r in eng.results()}
    for cfg, rid in zip(cfgs, ids):
        assert recs[rid]["status"] == "ok", recs[rid]
        np.testing.assert_array_equal(recs[rid]["T"], solve(cfg).T)


@pytest.mark.parametrize("dtype", ["float32", "bfloat16"])
def test_low_precision_lanes_identical_across_depths(dtype):
    """f32/bf16 results must not depend on the dispatch depth: the depth
    changes WHEN boundaries are inspected, never what the device steps."""
    cfgs = [HeatConfig(n=12, ntime=9, dtype=dtype, bc="edges"),
            HeatConfig(n=16, ntime=14, dtype=dtype, bc="ghost",
                       ic="uniform"),
            HeatConfig(n=10, ntime=21, dtype=dtype, bc="edges", nu=0.1)]
    fields = {}
    for depth in (0, 2, 3):
        eng = Engine(quiet(lanes=2, chunk=4, buckets=(16,),
                           dispatch_depth=depth))
        ids = [eng.submit(c) for c in cfgs]
        recs = {r["id"]: r for r in eng.results()}
        fields[depth] = [np.asarray(recs[rid]["T"], np.float32)
                         for rid in ids]
    for cfg, a, b, c in zip(cfgs, fields[0], fields[2], fields[3]):
        solo = np.asarray(solve(cfg).T, np.float32)
        np.testing.assert_array_equal(a, solo)
        np.testing.assert_array_equal(b, solo)
        np.testing.assert_array_equal(c, solo)


def test_dispatch_does_not_fence():
    """Regression (ISSUE 4): the hot loop must dispatch ahead — with
    depth 2, TWO chunk programs are queued before the first boundary
    fetch happens, and dispatching itself never touches host memory.
    host_fetch is the one D2H seam, so event order proves the shape."""
    events = []
    real_fetch = engine_mod.host_fetch
    real_dispatch = LaneEngine.dispatch_chunk

    def spy_fetch(x):
        events.append("fetch")
        return real_fetch(x)

    def spy_dispatch(self, k=None):
        events.append("dispatch")
        return real_dispatch(self, k)

    cfg = HeatConfig(n=16, ntime=32, dtype="float64")  # 4 chunks of 8
    eng = Engine(quiet(lanes=1, chunk=8, buckets=(16,), dispatch_depth=2))
    eng.submit(cfg)
    try:
        engine_mod.host_fetch = spy_fetch
        LaneEngine.dispatch_chunk = spy_dispatch
        recs = eng.results()
    finally:
        engine_mod.host_fetch = real_fetch
        LaneEngine.dispatch_chunk = real_dispatch
    assert recs[0]["status"] == "ok"
    # the pipeline primes to depth 2 before the first boundary D2H
    assert events[:3] == ["dispatch", "dispatch", "fetch"], events
    assert events.count("dispatch") == 4
    # every boundary was inspected exactly once, none re-fetched
    assert eng.boundary_waits == eng.chunks_dispatched == 4


def test_lane_tiers_share_programs_across_uneven_waves():
    """The lane-tier rule: waves of 3 then 5 requests under a --lanes 4
    cap round up to the SAME tier (4) and reuse one compiled stepping
    program; under a cap of 8 they land on tiers 4 and 8 (two programs,
    not three when a wave of 6 follows)."""
    assert [lane_tier(n, 4) for n in (1, 2, 3, 4, 5, 8)] == [1, 2, 4, 4, 4, 4]
    assert [lane_tier(n, 8) for n in (3, 5, 6, 9)] == [4, 8, 8, 8]

    def wave(eng, count):
        ids = [eng.submit(HeatConfig(n=12, ntime=6, dtype="float64"))
               for _ in range(count)]
        recs = {r["id"]: r for r in eng.results()}
        assert all(recs[i]["status"] == "ok" for i in ids)

    eng = Engine(quiet(lanes=4, chunk=4, buckets=(16,)))
    wave(eng, 3)
    assert eng.step_compiles == 1          # tier 4
    wave(eng, 5)
    assert eng.step_compiles == 1          # tier 4 again: warm reuse
    eng8 = Engine(quiet(lanes=8, chunk=4, buckets=(16,)))
    wave(eng8, 3)
    wave(eng8, 5)
    wave(eng8, 6)
    assert eng8.step_compiles == 2         # tiers 4 and 8, wave 3 reuses


def test_tail_chunks_bounded_compiles_and_exact():
    """Tail-waste fix: when every live lane's countdown drops below the
    chunk, the group switches to the quarter-chunk tail program — at most
    ONE tail compile per (bucket, lane-tier) across waves, and the
    results stay bit-identical."""
    assert tail_size(16) == 4 and tail_size(4) == 1
    assert tail_size(2) == 1 and tail_size(1) is None
    cfgs = [HeatConfig(n=12, ntime=21, dtype="float64"),
            HeatConfig(n=12, ntime=10, dtype="float64", nu=0.1)]
    eng = Engine(quiet(lanes=2, chunk=16, buckets=(16,)))
    ids = [eng.submit(c) for c in cfgs]
    recs = {r["id"]: r for r in eng.results()}
    for cfg, rid in zip(cfgs, ids):
        np.testing.assert_array_equal(recs[rid]["T"], solve(cfg).T)
    assert eng.tail_chunks >= 1
    assert eng.tail_compiles == 1
    # a second same-tier wave hitting the tail regime reuses the compiled
    # tail (two requests again — one request would be tier 1, a new combo)
    wave2 = [HeatConfig(n=12, ntime=5, dtype="float64"),
             HeatConfig(n=12, ntime=7, dtype="float64")]
    ids = [eng.submit(c) for c in wave2]
    recs = {r["id"]: r for r in eng.results()}
    for cfg, rid in zip(wave2, ids):
        np.testing.assert_array_equal(recs[rid]["T"], solve(cfg).T)
    assert eng.tail_compiles == 1 and eng.step_compiles == 1


def test_sink_error_isolated_under_async_extraction(tmp_path):
    """Fault isolation survives the async-extraction rework at depth > 1:
    the failing request's D2H + write run in the writer thread, fail that
    record, and the pipelined lanes keep draining bit-exact."""
    out = tmp_path / "results"
    eng = Engine(quiet(lanes=2, chunk=4, buckets=(32,), out_dir=str(out),
                       keep_fields=True, dispatch_depth=3))
    good1 = eng.submit(HeatConfig(n=16, ntime=10, dtype="float64"))
    bad = eng.submit(HeatConfig(n=16, ntime=10, dtype="float64",
                                inject="sink-error@0:times=99"))
    good2 = eng.submit(HeatConfig(n=16, ntime=10, dtype="float64", nu=0.1))
    recs = {r["id"]: r for r in eng.results()}
    assert recs[bad]["status"] == "error"
    assert "injected transient sink error" in recs[bad]["error"]
    assert not (out / f"{bad}.npz").exists()
    for rid in (good1, good2):
        assert recs[rid]["status"] == "ok"
        with np.load(out / f"{rid}.npz") as z:
            np.testing.assert_array_equal(z["T"], recs[rid]["T"])
    solo = solve(HeatConfig(n=16, ntime=10, dtype="float64")).T
    np.testing.assert_array_equal(recs[good1]["T"], solo)


def test_summary_surfaces_dispatch_counters():
    eng = Engine(quiet(lanes=2, chunk=8, buckets=(16,)))
    for _ in range(3):
        eng.submit(HeatConfig(n=12, ntime=20, dtype="float64"))
    eng.results()
    s = eng.summary()
    assert s["dispatch_depth"] == 2
    assert s["chunks_dispatched"] >= 3
    assert s["boundary_waits"] >= 1 and s["boundary_wait_s"] >= 0
    assert set(s) >= {"tail_chunks", "tail_compiles", "device_idle_s"}
    # and the run left a Timing carrying the serve fields
    assert eng.timing is not None and eng.timing.dispatch_depth == 2
    assert any("serve dispatch" in l for l in eng.timing.report_lines())


# --- admission / rejection --------------------------------------------------


def test_bucket_overflow_is_a_per_request_rejection():
    eng = Engine(quiet(buckets=(32, 64)))
    big = eng.submit(HeatConfig(n=100, ntime=5))
    ok = eng.submit(HeatConfig(n=16, ntime=5, dtype="float64"))
    recs = {r["id"]: r for r in eng.results()}
    assert recs[big]["status"] == "rejected"
    assert "bucket-overflow" in recs[big]["error"]
    assert recs[ok]["status"] == "ok"  # the engine kept serving


def test_periodic_bc_rejected_not_mis_served():
    eng = Engine(quiet(buckets=(32,)))
    rid = eng.submit(HeatConfig(n=16, ntime=5, bc="periodic"))
    rec = eng.results()[0]
    assert rec["status"] == "rejected" and "periodic" in rec["error"]
    assert rid == rec["id"]


def test_bucket_selection_smallest_fit():
    eng = Engine(quiet(lanes=1, buckets=(64, 16, 32)))
    rids = [eng.submit(HeatConfig(n=n, ntime=1, dtype="float64"))
            for n in (16, 17, 33)]
    recs = {r["id"]: r for r in eng.results()}
    assert [recs[r]["bucket"] for r in rids] == [16, 32, 64]


def test_lane_engine_rejects_periodic_and_bad_geometry():
    with pytest.raises(ValueError, match="periodic"):
        LaneEngine(BucketKey(2, 16, "float64", "periodic"), 1, 4)
    key = BucketKey(2, 16, "float64", "edges")
    with pytest.raises(ValueError, match="exceeds bucket"):
        lane_buffer(key, np.ones((32, 32)), 1.0)
    with pytest.raises(ValueError, match="square"):
        lane_buffer(key, np.ones((8, 4)), 1.0)


def test_queue_wait_and_lane_metadata_recorded():
    eng = Engine(quiet(lanes=1, chunk=4, buckets=(16,)))
    for _ in range(3):
        eng.submit(HeatConfig(n=8, ntime=8, dtype="float64"))
    recs = eng.results()
    assert all(r["lane"] == 0 for r in recs)
    assert all(r["queue_wait_s"] >= 0 for r in recs)
    assert all(r["steps_per_s"] > 0 for r in recs)
    # FIFO admission: later submits waited at least as long
    waits = [r["queue_wait_s"] for r in recs]
    assert waits == sorted(waits)


# --- writeback + fault isolation -------------------------------------------


def test_sink_error_fails_one_request_engine_keeps_draining(tmp_path):
    """Acceptance: a sink-error injected on one request's writeback fails
    THAT record; the other lanes' results land intact."""
    out = tmp_path / "results"
    eng = Engine(quiet(lanes=2, chunk=4, buckets=(32,), out_dir=str(out),
                       keep_fields=True))
    good1 = eng.submit(HeatConfig(n=16, ntime=10, dtype="float64"))
    bad = eng.submit(HeatConfig(n=16, ntime=10, dtype="float64",
                                inject="sink-error@0:times=99"))
    good2 = eng.submit(HeatConfig(n=16, ntime=10, dtype="float64", nu=0.1))
    recs = {r["id"]: r for r in eng.results()}
    assert recs[bad]["status"] == "error"
    assert "injected transient sink error" in recs[bad]["error"]
    assert not (out / f"{bad}.npz").exists()
    for rid in (good1, good2):
        assert recs[rid]["status"] == "ok"
        with np.load(out / f"{rid}.npz") as z:
            np.testing.assert_array_equal(z["T"], recs[rid]["T"])


def test_transient_sink_error_recovers_via_writer_retry(tmp_path):
    """times=1 is within SnapshotWriter's bounded retry budget: the
    request must end ok, not error."""
    out = tmp_path / "results"
    eng = Engine(quiet(lanes=1, chunk=4, buckets=(32,), out_dir=str(out)))
    rid = eng.submit(HeatConfig(n=16, ntime=10, dtype="float64",
                                inject="sink-error@0:times=1"))
    rec = eng.results()[0]
    assert rec["status"] == "ok"
    assert (out / f"{rid}.npz").exists()


def test_result_files_atomic_and_loadable(tmp_path):
    out = tmp_path / "r"
    cfg = HeatConfig(n=12, ntime=6, dtype="float64")
    eng = Engine(quiet(lanes=1, chunk=4, buckets=(16,), out_dir=str(out)))
    rid = eng.submit(cfg)
    eng.results()
    assert not list(out.glob("*.tmp"))  # atomic publish: no torn temps
    with np.load(out / f"{rid}.npz") as z:
        np.testing.assert_array_equal(z["T"], solve(cfg).T)
        assert int(z["step"]) == cfg.ntime


# --- request JSONL + CLI ----------------------------------------------------


def test_config_from_request_validates_and_coerces():
    cfg = config_from_request({"id": "x", "n": 24.0, "ntime": 7,
                               "nu": 0.1, "bc": "ghost"})
    assert cfg.n == 24 and isinstance(cfg.n, int)
    assert cfg.nu == 0.1 and cfg.bc == "ghost"
    with pytest.raises(ValueError, match="unknown request key"):
        config_from_request({"n": 8, "backend": "pallas"})
    with pytest.raises(ValueError):
        config_from_request({"n": 8, "dtype": "float16"})


def test_serve_cli_end_to_end(tmp_cwd, capsys):
    from heat_tpu.cli import main

    reqs = tmp_cwd / "reqs.jsonl"
    reqs.write_text(
        "# comment line\n"
        '{"id": "a", "n": 24, "ntime": 16, "dtype": "float64"}\n'
        "\n"
        '{"n": 40, "ntime": 8, "bc": "ghost", "ic": "uniform", '
        '"dtype": "float64"}\n')
    rc = main(["serve", "--requests", "reqs.jsonl", "--lanes", "2",
               "--chunk", "8", "--buckets", "32,48", "--out-dir", "res",
               "--json"])
    out = capsys.readouterr().out
    assert rc == 0
    records = [json.loads(l) for l in out.splitlines()
               if l.startswith("{") and '"serve_request"' in l]
    assert {r["id"] for r in records} == {"a", "req-0001"}
    assert all(r["status"] == "ok" for r in records)
    # the library-visible result equals the solo run of the same request
    with np.load(tmp_cwd / "res" / "a.npz") as z:
        solo = solve(HeatConfig(n=24, ntime=16, dtype="float64")).T
        np.testing.assert_array_equal(z["T"], solo)
    assert "served 2 request(s): 2 ok" in out


def test_serve_cli_bad_requests_nonzero_exit(tmp_cwd, capsys):
    from heat_tpu.cli import main

    (tmp_cwd / "reqs.jsonl").write_text(
        '{"n": 9999, "ntime": 1}\n'      # bucket overflow
        'garbage\n'                       # parse failure
        '{"n": 16, "ntime": 2, "dtype": "float64"}\n')
    rc = main(["serve", "--requests", "reqs.jsonl", "--buckets", "64"])
    out = capsys.readouterr().out
    assert rc == 1
    assert "1 ok" in out and "2 rejected" in out


def test_serve_cli_dispatch_depth_off_and_bad_value(tmp_cwd, capsys):
    from heat_tpu.cli import main

    reqs = tmp_cwd / "reqs.jsonl"
    reqs.write_text('{"id": "a", "n": 16, "ntime": 12, "dtype": "float64"}\n')
    rc = main(["serve", "--requests", "reqs.jsonl", "--buckets", "16",
               "--chunk", "4", "--dispatch-depth", "off"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "dispatch: depth 0" in out
    rc = main(["serve", "--requests", "reqs.jsonl", "--buckets", "16",
               "--dispatch-depth", "sideways"])
    assert rc == 2
    assert "dispatch-depth" in capsys.readouterr().err


def test_serve_lab_ab_harness_smoke(tmp_path, capsys):
    """The serve_lab A/B harness (dispatch-ahead vs sync vs sequential)
    runs end-to-end on a tiny 2-lane workload and emits every field the
    committed artifact relies on. Speed thresholds deliberately NOT
    asserted — 6 requests on a loaded CI box prove plumbing, not perf."""
    import importlib.util
    import sys
    from pathlib import Path

    bench_dir = Path(__file__).resolve().parent.parent / "benchmarks"
    sys.path.insert(0, str(bench_dir))
    try:
        spec = importlib.util.spec_from_file_location(
            "serve_lab_smoke", bench_dir / "serve_lab.py")
        serve_lab = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(serve_lab)
        out = tmp_path / "serve_lab.json"
        serve_lab.main(["--requests", "6", "--lanes", "2", "--chunk", "8",
                        "--out", str(out)])
    finally:
        sys.path.remove(str(bench_dir))
    rec = json.loads(out.read_text())
    assert rec["bench"] == "serve_lab"
    for side in ("engine", "engine_sync"):
        assert rec[side]["ok"] == 6
        assert rec[side]["bit_identical_sample"] is True
        assert rec[side]["boundary_wait_s"] >= 0
        assert "device_idle_frac_est" in rec[side]
    assert rec["engine"]["dispatch_depth"] == 2
    assert rec["engine_sync"]["dispatch_depth"] == 0
    assert rec["dispatch_ab_speedup"] is not None
    assert rec["one_compile_per_bucket_lane_tier"] is True


def test_serve_cli_missing_file(tmp_cwd, capsys):
    from heat_tpu.cli import main

    rc = main(["serve", "--requests", "nope.jsonl"])
    assert rc == 2
    assert "not found" in capsys.readouterr().err
