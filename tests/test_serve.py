"""Serving engine: batched-lane correctness, continuous batching, bucket
admission, per-request fault isolation, and the serve CLI.

The load-bearing contract is bit-identity: a request served through the
vmapped masked lanes must produce, on CPU at the same dtype, the exact
bytes a solo ``heat-tpu run`` of the same config produces — including
requests admitted mid-flight into a lane another request just vacated
(the continuous-batching path)."""

import json

import numpy as np
import pytest

from heat_tpu.backends import solve
from heat_tpu.config import HeatConfig, config_from_request
from heat_tpu.runtime import faults
from heat_tpu.serve import Engine, ServeConfig
from heat_tpu.serve.engine import BucketKey, LaneEngine, lane_buffer


@pytest.fixture(autouse=True)
def _fresh_faults():
    faults.reset()
    yield
    faults.reset()


def quiet(**kw) -> ServeConfig:
    kw.setdefault("emit_records", False)
    return ServeConfig(**kw)


# --- batched-engine bit-identity -------------------------------------------


MIXED_REQUESTS = [
    # mixed sizes, BCs, diffusivities, step counts — all one engine run;
    # 6 requests over 2 lanes forces continuous-batching admission
    HeatConfig(n=17, ntime=37, dtype="float64", bc="edges", ic="hat"),
    HeatConfig(n=32, ntime=50, dtype="float64", bc="ghost", ic="uniform"),
    HeatConfig(n=24, ntime=5, dtype="float64", bc="edges", ic="hat_small",
               nu=0.1),
    HeatConfig(n=40, ntime=20, dtype="float64", bc="edges", ic="hat"),
    HeatConfig(n=20, ntime=64, dtype="float64", bc="ghost", ic="hat",
               bc_value=2.5),
    HeatConfig(n=17, ntime=3, dtype="float64", bc="ghost", ic="hat_half"),
]


def test_batched_lanes_bit_identical_to_solo_runs():
    """Acceptance: every lane's final field == the solo run of the same
    config, bitwise, including lanes filled mid-flight (6 requests, 2
    lanes -> 4 of them are continuous-batching admits)."""
    eng = Engine(quiet(lanes=2, chunk=8, buckets=(32, 48)))
    ids = [eng.submit(cfg) for cfg in MIXED_REQUESTS]
    recs = {r["id"]: r for r in eng.results()}
    for cfg, rid in zip(MIXED_REQUESTS, ids):
        rec = recs[rid]
        assert rec["status"] == "ok", rec
        solo = solve(cfg).T
        assert solo.dtype == rec["T"].dtype
        np.testing.assert_array_equal(rec["T"], solo)


def test_mid_flight_admit_into_freed_lane_is_exact():
    """One lane, three requests: requests 2 and 3 can ONLY run by being
    swapped into the lane request 1 finished in. Different step counts so
    the masked per-step countdown (not the chunk size) sets each stop."""
    cfgs = [HeatConfig(n=16, ntime=k, dtype="float64", bc="edges")
            for k in (7, 19, 30)]  # none a multiple of chunk=8
    eng = Engine(quiet(lanes=1, chunk=8, buckets=(16,)))
    ids = [eng.submit(c) for c in cfgs]
    recs = {r["id"]: r for r in eng.results()}
    for cfg, rid in zip(cfgs, ids):
        np.testing.assert_array_equal(recs[rid]["T"], solve(cfg).T)
    # one lane means one (bucket, lane-count) combo: exactly one compile
    assert eng.step_compiles == 1


def test_float32_and_bfloat16_lanes_match_solo():
    for dtype in ("float32", "bfloat16"):
        cfgs = [HeatConfig(n=12, ntime=9, dtype=dtype, bc="edges"),
                HeatConfig(n=16, ntime=14, dtype=dtype, bc="ghost",
                           ic="uniform")]
        eng = Engine(quiet(lanes=2, chunk=4, buckets=(16,)))
        ids = [eng.submit(c) for c in cfgs]
        recs = {r["id"]: r for r in eng.results()}
        for cfg, rid in zip(cfgs, ids):
            solo = solve(cfg).T
            assert np.array_equal(np.asarray(recs[rid]["T"], np.float32),
                                  np.asarray(solo, np.float32))


def test_3d_requests_served():
    cfg = HeatConfig(n=10, ntime=6, ndim=3, dtype="float64", bc="ghost",
                     ic="uniform", sigma=0.1)
    eng = Engine(quiet(lanes=2, chunk=4, buckets=(12,)))
    rid = eng.submit(cfg)
    rec = eng.results()[0]
    assert rec["status"] == "ok" and rec["id"] == rid
    np.testing.assert_array_equal(rec["T"], solve(cfg).T)


def test_zero_step_request_returns_ic():
    cfg = HeatConfig(n=8, ntime=0, dtype="float64")
    eng = Engine(quiet(lanes=1, chunk=4, buckets=(8,)))
    eng.submit(cfg)
    np.testing.assert_array_equal(eng.results()[0]["T"], solve(cfg).T)


def test_compile_count_one_per_bucket_lane_combo():
    """Acceptance: at most one stepping compile per (bucket, lane-count),
    however many requests flow through — and a second wave of submits
    reuses the warm programs (zero new compiles)."""
    cfgs = [HeatConfig(n=n, ntime=4, dtype="float64")
            for n in (8, 10, 12, 14, 16, 9, 11, 13)]
    eng = Engine(quiet(lanes=3, chunk=4, buckets=(12, 16)))
    for c in cfgs:
        eng.submit(c)
    eng.results()
    # two buckets, both with >= 3 requests -> exactly two combos
    assert eng.step_compiles == 2
    before = eng.step_compiles
    for c in cfgs:
        eng.submit(c)
    recs = eng.results()
    assert eng.step_compiles == before  # warm reuse across waves
    assert sum(r["status"] == "ok" for r in recs) == 2 * len(cfgs)


# --- admission / rejection --------------------------------------------------


def test_bucket_overflow_is_a_per_request_rejection():
    eng = Engine(quiet(buckets=(32, 64)))
    big = eng.submit(HeatConfig(n=100, ntime=5))
    ok = eng.submit(HeatConfig(n=16, ntime=5, dtype="float64"))
    recs = {r["id"]: r for r in eng.results()}
    assert recs[big]["status"] == "rejected"
    assert "bucket-overflow" in recs[big]["error"]
    assert recs[ok]["status"] == "ok"  # the engine kept serving


def test_periodic_bc_rejected_not_mis_served():
    eng = Engine(quiet(buckets=(32,)))
    rid = eng.submit(HeatConfig(n=16, ntime=5, bc="periodic"))
    rec = eng.results()[0]
    assert rec["status"] == "rejected" and "periodic" in rec["error"]
    assert rid == rec["id"]


def test_bucket_selection_smallest_fit():
    eng = Engine(quiet(lanes=1, buckets=(64, 16, 32)))
    rids = [eng.submit(HeatConfig(n=n, ntime=1, dtype="float64"))
            for n in (16, 17, 33)]
    recs = {r["id"]: r for r in eng.results()}
    assert [recs[r]["bucket"] for r in rids] == [16, 32, 64]


def test_lane_engine_rejects_periodic_and_bad_geometry():
    with pytest.raises(ValueError, match="periodic"):
        LaneEngine(BucketKey(2, 16, "float64", "periodic"), 1, 4)
    key = BucketKey(2, 16, "float64", "edges")
    with pytest.raises(ValueError, match="exceeds bucket"):
        lane_buffer(key, np.ones((32, 32)), 1.0)
    with pytest.raises(ValueError, match="square"):
        lane_buffer(key, np.ones((8, 4)), 1.0)


def test_queue_wait_and_lane_metadata_recorded():
    eng = Engine(quiet(lanes=1, chunk=4, buckets=(16,)))
    for _ in range(3):
        eng.submit(HeatConfig(n=8, ntime=8, dtype="float64"))
    recs = eng.results()
    assert all(r["lane"] == 0 for r in recs)
    assert all(r["queue_wait_s"] >= 0 for r in recs)
    assert all(r["steps_per_s"] > 0 for r in recs)
    # FIFO admission: later submits waited at least as long
    waits = [r["queue_wait_s"] for r in recs]
    assert waits == sorted(waits)


# --- writeback + fault isolation -------------------------------------------


def test_sink_error_fails_one_request_engine_keeps_draining(tmp_path):
    """Acceptance: a sink-error injected on one request's writeback fails
    THAT record; the other lanes' results land intact."""
    out = tmp_path / "results"
    eng = Engine(quiet(lanes=2, chunk=4, buckets=(32,), out_dir=str(out),
                       keep_fields=True))
    good1 = eng.submit(HeatConfig(n=16, ntime=10, dtype="float64"))
    bad = eng.submit(HeatConfig(n=16, ntime=10, dtype="float64",
                                inject="sink-error@0:times=99"))
    good2 = eng.submit(HeatConfig(n=16, ntime=10, dtype="float64", nu=0.1))
    recs = {r["id"]: r for r in eng.results()}
    assert recs[bad]["status"] == "error"
    assert "injected transient sink error" in recs[bad]["error"]
    assert not (out / f"{bad}.npz").exists()
    for rid in (good1, good2):
        assert recs[rid]["status"] == "ok"
        with np.load(out / f"{rid}.npz") as z:
            np.testing.assert_array_equal(z["T"], recs[rid]["T"])


def test_transient_sink_error_recovers_via_writer_retry(tmp_path):
    """times=1 is within SnapshotWriter's bounded retry budget: the
    request must end ok, not error."""
    out = tmp_path / "results"
    eng = Engine(quiet(lanes=1, chunk=4, buckets=(32,), out_dir=str(out)))
    rid = eng.submit(HeatConfig(n=16, ntime=10, dtype="float64",
                                inject="sink-error@0:times=1"))
    rec = eng.results()[0]
    assert rec["status"] == "ok"
    assert (out / f"{rid}.npz").exists()


def test_result_files_atomic_and_loadable(tmp_path):
    out = tmp_path / "r"
    cfg = HeatConfig(n=12, ntime=6, dtype="float64")
    eng = Engine(quiet(lanes=1, chunk=4, buckets=(16,), out_dir=str(out)))
    rid = eng.submit(cfg)
    eng.results()
    assert not list(out.glob("*.tmp"))  # atomic publish: no torn temps
    with np.load(out / f"{rid}.npz") as z:
        np.testing.assert_array_equal(z["T"], solve(cfg).T)
        assert int(z["step"]) == cfg.ntime


# --- request JSONL + CLI ----------------------------------------------------


def test_config_from_request_validates_and_coerces():
    cfg = config_from_request({"id": "x", "n": 24.0, "ntime": 7,
                               "nu": 0.1, "bc": "ghost"})
    assert cfg.n == 24 and isinstance(cfg.n, int)
    assert cfg.nu == 0.1 and cfg.bc == "ghost"
    with pytest.raises(ValueError, match="unknown request key"):
        config_from_request({"n": 8, "backend": "pallas"})
    with pytest.raises(ValueError):
        config_from_request({"n": 8, "dtype": "float16"})


def test_serve_cli_end_to_end(tmp_cwd, capsys):
    from heat_tpu.cli import main

    reqs = tmp_cwd / "reqs.jsonl"
    reqs.write_text(
        "# comment line\n"
        '{"id": "a", "n": 24, "ntime": 16, "dtype": "float64"}\n'
        "\n"
        '{"n": 40, "ntime": 8, "bc": "ghost", "ic": "uniform", '
        '"dtype": "float64"}\n')
    rc = main(["serve", "--requests", "reqs.jsonl", "--lanes", "2",
               "--chunk", "8", "--buckets", "32,48", "--out-dir", "res",
               "--json"])
    out = capsys.readouterr().out
    assert rc == 0
    records = [json.loads(l) for l in out.splitlines()
               if l.startswith("{") and '"serve_request"' in l]
    assert {r["id"] for r in records} == {"a", "req-0001"}
    assert all(r["status"] == "ok" for r in records)
    # the library-visible result equals the solo run of the same request
    with np.load(tmp_cwd / "res" / "a.npz") as z:
        solo = solve(HeatConfig(n=24, ntime=16, dtype="float64")).T
        np.testing.assert_array_equal(z["T"], solo)
    assert "served 2 request(s): 2 ok" in out


def test_serve_cli_bad_requests_nonzero_exit(tmp_cwd, capsys):
    from heat_tpu.cli import main

    (tmp_cwd / "reqs.jsonl").write_text(
        '{"n": 9999, "ntime": 1}\n'      # bucket overflow
        'garbage\n'                       # parse failure
        '{"n": 16, "ntime": 2, "dtype": "float64"}\n')
    rc = main(["serve", "--requests", "reqs.jsonl", "--buckets", "64"])
    out = capsys.readouterr().out
    assert rc == 1
    assert "1 ok" in out and "2 rejected" in out


def test_serve_cli_missing_file(tmp_cwd, capsys):
    from heat_tpu.cli import main

    rc = main(["serve", "--requests", "nope.jsonl"])
    assert rc == 2
    assert "not found" in capsys.readouterr().err
