"""Serving engine: batched-lane correctness, continuous batching, bucket
admission, per-request fault isolation, and the serve CLI.

The load-bearing contract is bit-identity: a request served through the
vmapped masked lanes must produce, on CPU at the same dtype, the exact
bytes a solo ``heat-tpu run`` of the same config produces — including
requests admitted mid-flight into a lane another request just vacated
(the continuous-batching path)."""

import json

import numpy as np
import pytest

from heat_tpu.backends import solve
from heat_tpu.config import HeatConfig, config_from_request
from heat_tpu.runtime import faults
from heat_tpu.serve import Engine, ServeConfig
from heat_tpu.serve import engine as engine_mod
from heat_tpu.serve.engine import (BucketKey, LaneEngine, lane_buffer,
                                   lane_tier, tail_size)


@pytest.fixture(autouse=True)
def _fresh_faults():
    faults.reset()
    yield
    faults.reset()


def quiet(**kw) -> ServeConfig:
    kw.setdefault("emit_records", False)
    return ServeConfig(**kw)


# --- batched-engine bit-identity -------------------------------------------


MIXED_REQUESTS = [
    # mixed sizes, BCs, diffusivities, step counts — all one engine run;
    # 6 requests over 2 lanes forces continuous-batching admission
    HeatConfig(n=17, ntime=37, dtype="float64", bc="edges", ic="hat"),
    HeatConfig(n=32, ntime=50, dtype="float64", bc="ghost", ic="uniform"),
    HeatConfig(n=24, ntime=5, dtype="float64", bc="edges", ic="hat_small",
               nu=0.1),
    HeatConfig(n=40, ntime=20, dtype="float64", bc="edges", ic="hat"),
    HeatConfig(n=20, ntime=64, dtype="float64", bc="ghost", ic="hat",
               bc_value=2.5),
    HeatConfig(n=17, ntime=3, dtype="float64", bc="ghost", ic="hat_half"),
]


def test_batched_lanes_bit_identical_to_solo_runs():
    """Acceptance: every lane's final field == the solo run of the same
    config, bitwise, including lanes filled mid-flight (6 requests, 2
    lanes -> 4 of them are continuous-batching admits)."""
    eng = Engine(quiet(lanes=2, chunk=8, buckets=(32, 48)))
    ids = [eng.submit(cfg) for cfg in MIXED_REQUESTS]
    recs = {r["id"]: r for r in eng.results()}
    for cfg, rid in zip(MIXED_REQUESTS, ids):
        rec = recs[rid]
        assert rec["status"] == "ok", rec
        solo = solve(cfg).T
        assert solo.dtype == rec["T"].dtype
        np.testing.assert_array_equal(rec["T"], solo)


def test_mid_flight_admit_into_freed_lane_is_exact():
    """One lane, three requests: requests 2 and 3 can ONLY run by being
    swapped into the lane request 1 finished in. Different step counts so
    the masked per-step countdown (not the chunk size) sets each stop."""
    cfgs = [HeatConfig(n=16, ntime=k, dtype="float64", bc="edges")
            for k in (7, 19, 30)]  # none a multiple of chunk=8
    eng = Engine(quiet(lanes=1, chunk=8, buckets=(16,)))
    ids = [eng.submit(c) for c in cfgs]
    recs = {r["id"]: r for r in eng.results()}
    for cfg, rid in zip(cfgs, ids):
        np.testing.assert_array_equal(recs[rid]["T"], solve(cfg).T)
    # one lane means one (bucket, lane-count) combo: exactly one compile
    assert eng.step_compiles == 1


def test_float32_and_bfloat16_lanes_match_solo():
    for dtype in ("float32", "bfloat16"):
        cfgs = [HeatConfig(n=12, ntime=9, dtype=dtype, bc="edges"),
                HeatConfig(n=16, ntime=14, dtype=dtype, bc="ghost",
                           ic="uniform")]
        eng = Engine(quiet(lanes=2, chunk=4, buckets=(16,)))
        ids = [eng.submit(c) for c in cfgs]
        recs = {r["id"]: r for r in eng.results()}
        for cfg, rid in zip(cfgs, ids):
            solo = solve(cfg).T
            assert np.array_equal(np.asarray(recs[rid]["T"], np.float32),
                                  np.asarray(solo, np.float32))


def test_3d_requests_served():
    cfg = HeatConfig(n=10, ntime=6, ndim=3, dtype="float64", bc="ghost",
                     ic="uniform", sigma=0.1)
    eng = Engine(quiet(lanes=2, chunk=4, buckets=(12,)))
    rid = eng.submit(cfg)
    rec = eng.results()[0]
    assert rec["status"] == "ok" and rec["id"] == rid
    np.testing.assert_array_equal(rec["T"], solve(cfg).T)


def test_zero_step_request_returns_ic():
    cfg = HeatConfig(n=8, ntime=0, dtype="float64")
    eng = Engine(quiet(lanes=1, chunk=4, buckets=(8,)))
    eng.submit(cfg)
    np.testing.assert_array_equal(eng.results()[0]["T"], solve(cfg).T)


def test_compile_count_one_per_bucket_lane_combo():
    """Acceptance: at most one stepping compile per (bucket, lane-count),
    however many requests flow through — and a second wave of submits
    reuses the warm programs (zero new compiles)."""
    cfgs = [HeatConfig(n=n, ntime=4, dtype="float64")
            for n in (8, 10, 12, 14, 16, 9, 11, 13)]
    eng = Engine(quiet(lanes=3, chunk=4, buckets=(12, 16)))
    for c in cfgs:
        eng.submit(c)
    eng.results()
    # two buckets, both with >= 3 requests -> exactly two combos
    assert eng.step_compiles == 2
    before = eng.step_compiles
    for c in cfgs:
        eng.submit(c)
    recs = eng.results()
    assert eng.step_compiles == before  # warm reuse across waves
    assert sum(r["status"] == "ok" for r in recs) == 2 * len(cfgs)


# --- dispatch-ahead (ISSUE 4) ----------------------------------------------


@pytest.mark.parametrize("depth", [0, 1, 2, 4])
def test_bit_identity_at_every_dispatch_depth(depth):
    """Acceptance: served results are bit-identical to solo runs at every
    dispatch depth — including depth=0 (the sync fallback) and depths
    deeper than the chunk count — with mid-flight admits (6+1 requests
    over 2 lanes) and a zero-step request in the mix."""
    cfgs = MIXED_REQUESTS + [HeatConfig(n=12, ntime=0, dtype="float64")]
    eng = Engine(quiet(lanes=2, chunk=8, buckets=(32, 48),
                       dispatch_depth=depth))
    ids = [eng.submit(cfg) for cfg in cfgs]
    recs = {r["id"]: r for r in eng.results()}
    for cfg, rid in zip(cfgs, ids):
        assert recs[rid]["status"] == "ok", recs[rid]
        np.testing.assert_array_equal(recs[rid]["T"], solve(cfg).T)


@pytest.mark.parametrize("dtype", ["float32", "bfloat16"])
def test_low_precision_lanes_identical_across_depths(dtype):
    """f32/bf16 results must not depend on the dispatch depth: the depth
    changes WHEN boundaries are inspected, never what the device steps."""
    cfgs = [HeatConfig(n=12, ntime=9, dtype=dtype, bc="edges"),
            HeatConfig(n=16, ntime=14, dtype=dtype, bc="ghost",
                       ic="uniform"),
            HeatConfig(n=10, ntime=21, dtype=dtype, bc="edges", nu=0.1)]
    fields = {}
    for depth in (0, 2, 3):
        eng = Engine(quiet(lanes=2, chunk=4, buckets=(16,),
                           dispatch_depth=depth))
        ids = [eng.submit(c) for c in cfgs]
        recs = {r["id"]: r for r in eng.results()}
        fields[depth] = [np.asarray(recs[rid]["T"], np.float32)
                         for rid in ids]
    for cfg, a, b, c in zip(cfgs, fields[0], fields[2], fields[3]):
        solo = np.asarray(solve(cfg).T, np.float32)
        np.testing.assert_array_equal(a, solo)
        np.testing.assert_array_equal(b, solo)
        np.testing.assert_array_equal(c, solo)


def test_dispatch_does_not_fence():
    """Regression (ISSUE 4): the hot loop must dispatch ahead — with
    depth 2, TWO chunk programs are queued before the first boundary
    fetch happens, and dispatching itself never touches host memory.
    host_fetch is the one D2H seam, so event order proves the shape."""
    events = []
    real_fetch = engine_mod.host_fetch
    real_dispatch = LaneEngine.dispatch_chunk

    def spy_fetch(x):
        events.append("fetch")
        return real_fetch(x)

    def spy_dispatch(self, k=None):
        events.append("dispatch")
        return real_dispatch(self, k)

    cfg = HeatConfig(n=16, ntime=32, dtype="float64")  # 4 chunks of 8
    eng = Engine(quiet(lanes=1, chunk=8, buckets=(16,), dispatch_depth=2))
    eng.submit(cfg)
    try:
        engine_mod.host_fetch = spy_fetch
        LaneEngine.dispatch_chunk = spy_dispatch
        recs = eng.results()
    finally:
        engine_mod.host_fetch = real_fetch
        LaneEngine.dispatch_chunk = real_dispatch
    assert recs[0]["status"] == "ok"
    # the pipeline primes to depth 2 before the first boundary D2H
    assert events[:3] == ["dispatch", "dispatch", "fetch"], events
    assert events.count("dispatch") == 4
    # every boundary was inspected exactly once, none re-fetched
    assert eng.boundary_waits == eng.chunks_dispatched == 4


def test_lane_tiers_share_programs_across_uneven_waves():
    """The lane-tier rule: waves of 3 then 5 requests under a --lanes 4
    cap round up to the SAME tier (4) and reuse one compiled stepping
    program; under a cap of 8 they land on tiers 4 and 8 (two programs,
    not three when a wave of 6 follows)."""
    assert [lane_tier(n, 4) for n in (1, 2, 3, 4, 5, 8)] == [1, 2, 4, 4, 4, 4]
    assert [lane_tier(n, 8) for n in (3, 5, 6, 9)] == [4, 8, 8, 8]

    def wave(eng, count):
        ids = [eng.submit(HeatConfig(n=12, ntime=6, dtype="float64"))
               for _ in range(count)]
        recs = {r["id"]: r for r in eng.results()}
        assert all(recs[i]["status"] == "ok" for i in ids)

    eng = Engine(quiet(lanes=4, chunk=4, buckets=(16,)))
    wave(eng, 3)
    assert eng.step_compiles == 1          # tier 4
    wave(eng, 5)
    assert eng.step_compiles == 1          # tier 4 again: warm reuse
    eng8 = Engine(quiet(lanes=8, chunk=4, buckets=(16,)))
    wave(eng8, 3)
    wave(eng8, 5)
    wave(eng8, 6)
    assert eng8.step_compiles == 2         # tiers 4 and 8, wave 3 reuses


def test_tail_chunks_bounded_compiles_and_exact():
    """Tail-waste fix: when every live lane's countdown drops below the
    chunk, the group switches to the quarter-chunk tail program — at most
    ONE tail compile per (bucket, lane-tier) across waves, and the
    results stay bit-identical."""
    assert tail_size(16) == 4 and tail_size(4) == 1
    assert tail_size(2) == 1 and tail_size(1) is None
    cfgs = [HeatConfig(n=12, ntime=21, dtype="float64"),
            HeatConfig(n=12, ntime=10, dtype="float64", nu=0.1)]
    eng = Engine(quiet(lanes=2, chunk=16, buckets=(16,)))
    ids = [eng.submit(c) for c in cfgs]
    recs = {r["id"]: r for r in eng.results()}
    for cfg, rid in zip(cfgs, ids):
        np.testing.assert_array_equal(recs[rid]["T"], solve(cfg).T)
    assert eng.tail_chunks >= 1
    assert eng.tail_compiles == 1
    # a second same-tier wave hitting the tail regime reuses the compiled
    # tail (two requests again — one request would be tier 1, a new combo)
    wave2 = [HeatConfig(n=12, ntime=5, dtype="float64"),
             HeatConfig(n=12, ntime=7, dtype="float64")]
    ids = [eng.submit(c) for c in wave2]
    recs = {r["id"]: r for r in eng.results()}
    for cfg, rid in zip(wave2, ids):
        np.testing.assert_array_equal(recs[rid]["T"], solve(cfg).T)
    assert eng.tail_compiles == 1 and eng.step_compiles == 1


def test_sink_error_isolated_under_async_extraction(tmp_path):
    """Fault isolation survives the async-extraction rework at depth > 1:
    the failing request's D2H + write run in the writer thread, fail that
    record, and the pipelined lanes keep draining bit-exact."""
    out = tmp_path / "results"
    eng = Engine(quiet(lanes=2, chunk=4, buckets=(32,), out_dir=str(out),
                       keep_fields=True, dispatch_depth=3))
    good1 = eng.submit(HeatConfig(n=16, ntime=10, dtype="float64"))
    bad = eng.submit(HeatConfig(n=16, ntime=10, dtype="float64",
                                inject="sink-error@0:times=99"))
    good2 = eng.submit(HeatConfig(n=16, ntime=10, dtype="float64", nu=0.1))
    recs = {r["id"]: r for r in eng.results()}
    assert recs[bad]["status"] == "error"
    assert "injected transient sink error" in recs[bad]["error"]
    assert not (out / f"{bad}.npz").exists()
    for rid in (good1, good2):
        assert recs[rid]["status"] == "ok"
        with np.load(out / f"{rid}.npz") as z:
            np.testing.assert_array_equal(z["T"], recs[rid]["T"])
    solo = solve(HeatConfig(n=16, ntime=10, dtype="float64")).T
    np.testing.assert_array_equal(recs[good1]["T"], solo)


def test_summary_surfaces_dispatch_counters():
    eng = Engine(quiet(lanes=2, chunk=8, buckets=(16,)))
    for _ in range(3):
        eng.submit(HeatConfig(n=12, ntime=20, dtype="float64"))
    eng.results()
    s = eng.summary()
    assert s["dispatch_depth"] == 2
    assert s["chunks_dispatched"] >= 3
    assert s["boundary_waits"] >= 1 and s["boundary_wait_s"] >= 0
    assert set(s) >= {"tail_chunks", "tail_compiles", "device_idle_s"}
    # and the run left a Timing carrying the serve fields
    assert eng.timing is not None and eng.timing.dispatch_depth == 2
    assert any("serve dispatch" in l for l in eng.timing.report_lines())


# --- admission / rejection --------------------------------------------------


def test_bucket_overflow_is_a_per_request_rejection():
    # mega_lanes=0 pins the single-tier shape (ISSUE 10: on a
    # multi-device host, overflow otherwise runs as a sharded mega-lane
    # — tests/test_serve_mega.py owns that path)
    eng = Engine(quiet(buckets=(32, 64), mega_lanes=0))
    big = eng.submit(HeatConfig(n=100, ntime=5))
    ok = eng.submit(HeatConfig(n=16, ntime=5, dtype="float64"))
    recs = {r["id"]: r for r in eng.results()}
    assert recs[big]["status"] == "rejected"
    assert "bucket-overflow" in recs[big]["error"]
    assert recs[big]["hint"] == "enable --mega-lanes"
    assert recs[ok]["status"] == "ok"  # the engine kept serving


def test_periodic_bc_rejected_not_mis_served():
    eng = Engine(quiet(buckets=(32,)))
    rid = eng.submit(HeatConfig(n=16, ntime=5, bc="periodic"))
    rec = eng.results()[0]
    assert rec["status"] == "rejected" and "periodic" in rec["error"]
    assert rid == rec["id"]


def test_bucket_selection_smallest_fit():
    eng = Engine(quiet(lanes=1, buckets=(64, 16, 32)))
    rids = [eng.submit(HeatConfig(n=n, ntime=1, dtype="float64"))
            for n in (16, 17, 33)]
    recs = {r["id"]: r for r in eng.results()}
    assert [recs[r]["bucket"] for r in rids] == [16, 32, 64]


def test_lane_engine_rejects_periodic_and_bad_geometry():
    with pytest.raises(ValueError, match="periodic"):
        LaneEngine(BucketKey(2, 16, "float64", "periodic"), 1, 4)
    key = BucketKey(2, 16, "float64", "edges")
    with pytest.raises(ValueError, match="exceeds bucket"):
        lane_buffer(key, np.ones((32, 32)), 1.0)
    with pytest.raises(ValueError, match="square"):
        lane_buffer(key, np.ones((8, 4)), 1.0)


def test_queue_wait_and_lane_metadata_recorded():
    eng = Engine(quiet(lanes=1, chunk=4, buckets=(16,)))
    for _ in range(3):
        eng.submit(HeatConfig(n=8, ntime=8, dtype="float64"))
    recs = eng.results()
    assert all(r["lane"] == 0 for r in recs)
    assert all(r["queue_wait_s"] >= 0 for r in recs)
    assert all(r["steps_per_s"] > 0 for r in recs)
    # FIFO admission: later submits waited at least as long
    waits = [r["queue_wait_s"] for r in recs]
    assert waits == sorted(waits)


# --- writeback + fault isolation -------------------------------------------


def test_sink_error_fails_one_request_engine_keeps_draining(tmp_path):
    """Acceptance: a sink-error injected on one request's writeback fails
    THAT record; the other lanes' results land intact."""
    out = tmp_path / "results"
    eng = Engine(quiet(lanes=2, chunk=4, buckets=(32,), out_dir=str(out),
                       keep_fields=True))
    good1 = eng.submit(HeatConfig(n=16, ntime=10, dtype="float64"))
    bad = eng.submit(HeatConfig(n=16, ntime=10, dtype="float64",
                                inject="sink-error@0:times=99"))
    good2 = eng.submit(HeatConfig(n=16, ntime=10, dtype="float64", nu=0.1))
    recs = {r["id"]: r for r in eng.results()}
    assert recs[bad]["status"] == "error"
    assert "injected transient sink error" in recs[bad]["error"]
    assert not (out / f"{bad}.npz").exists()
    for rid in (good1, good2):
        assert recs[rid]["status"] == "ok"
        with np.load(out / f"{rid}.npz") as z:
            np.testing.assert_array_equal(z["T"], recs[rid]["T"])


def test_transient_sink_error_recovers_via_writer_retry(tmp_path):
    """times=1 is within SnapshotWriter's bounded retry budget: the
    request must end ok, not error."""
    out = tmp_path / "results"
    eng = Engine(quiet(lanes=1, chunk=4, buckets=(32,), out_dir=str(out)))
    rid = eng.submit(HeatConfig(n=16, ntime=10, dtype="float64",
                                inject="sink-error@0:times=1"))
    rec = eng.results()[0]
    assert rec["status"] == "ok"
    assert (out / f"{rid}.npz").exists()


def test_result_files_atomic_and_loadable(tmp_path):
    out = tmp_path / "r"
    cfg = HeatConfig(n=12, ntime=6, dtype="float64")
    eng = Engine(quiet(lanes=1, chunk=4, buckets=(16,), out_dir=str(out)))
    rid = eng.submit(cfg)
    eng.results()
    assert not list(out.glob("*.tmp"))  # atomic publish: no torn temps
    with np.load(out / f"{rid}.npz") as z:
        np.testing.assert_array_equal(z["T"], solve(cfg).T)
        assert int(z["step"]) == cfg.ntime


# --- request JSONL + CLI ----------------------------------------------------


def test_config_from_request_validates_and_coerces():
    cfg = config_from_request({"id": "x", "n": 24.0, "ntime": 7,
                               "nu": 0.1, "bc": "ghost"})
    assert cfg.n == 24 and isinstance(cfg.n, int)
    assert cfg.nu == 0.1 and cfg.bc == "ghost"
    with pytest.raises(ValueError, match="unknown request key"):
        config_from_request({"n": 8, "backend": "pallas"})
    with pytest.raises(ValueError):
        config_from_request({"n": 8, "dtype": "float16"})


def test_serve_cli_end_to_end(tmp_cwd, capsys):
    from heat_tpu.cli import main

    reqs = tmp_cwd / "reqs.jsonl"
    reqs.write_text(
        "# comment line\n"
        '{"id": "a", "n": 24, "ntime": 16, "dtype": "float64"}\n'
        "\n"
        '{"n": 40, "ntime": 8, "bc": "ghost", "ic": "uniform", '
        '"dtype": "float64"}\n')
    rc = main(["serve", "--requests", "reqs.jsonl", "--lanes", "2",
               "--chunk", "8", "--buckets", "32,48", "--out-dir", "res",
               "--json"])
    out = capsys.readouterr().out
    assert rc == 0
    records = [json.loads(l) for l in out.splitlines()
               if l.startswith("{") and '"serve_request"' in l]
    assert {r["id"] for r in records} == {"a", "req-0001"}
    assert all(r["status"] == "ok" for r in records)
    # the library-visible result equals the solo run of the same request
    with np.load(tmp_cwd / "res" / "a.npz") as z:
        solo = solve(HeatConfig(n=24, ntime=16, dtype="float64")).T
        np.testing.assert_array_equal(z["T"], solo)
    assert "served 2 request(s): 2 ok" in out


def test_serve_cli_bad_requests_nonzero_exit(tmp_cwd, capsys):
    from heat_tpu.cli import main

    (tmp_cwd / "reqs.jsonl").write_text(
        '{"n": 9999, "ntime": 1}\n'      # bucket overflow
        'garbage\n'                       # parse failure
        '{"n": 16, "ntime": 2, "dtype": "float64"}\n')
    rc = main(["serve", "--requests", "reqs.jsonl", "--buckets", "64"])
    out = capsys.readouterr().out
    assert rc == 1
    assert "1 ok" in out and "2 rejected" in out


def test_serve_cli_dispatch_depth_off_and_bad_value(tmp_cwd, capsys):
    from heat_tpu.cli import main

    reqs = tmp_cwd / "reqs.jsonl"
    reqs.write_text('{"id": "a", "n": 16, "ntime": 12, "dtype": "float64"}\n')
    rc = main(["serve", "--requests", "reqs.jsonl", "--buckets", "16",
               "--chunk", "4", "--dispatch-depth", "off"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "dispatch: depth 0" in out
    rc = main(["serve", "--requests", "reqs.jsonl", "--buckets", "16",
               "--dispatch-depth", "sideways"])
    assert rc == 2
    assert "dispatch-depth" in capsys.readouterr().err


def test_serve_lab_ab_harness_smoke(tmp_path, capsys):
    """The serve_lab A/B harness (dispatch-ahead vs sync vs sequential)
    runs end-to-end on a tiny 2-lane workload and emits every field the
    committed artifact relies on. Speed thresholds deliberately NOT
    asserted — 6 requests on a loaded CI box prove plumbing, not perf."""
    import importlib.util
    import sys
    from pathlib import Path

    bench_dir = Path(__file__).resolve().parent.parent / "benchmarks"
    sys.path.insert(0, str(bench_dir))
    try:
        spec = importlib.util.spec_from_file_location(
            "serve_lab_smoke", bench_dir / "serve_lab.py")
        serve_lab = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(serve_lab)
        out = tmp_path / "serve_lab.json"
        serve_lab.main(["--requests", "6", "--lanes", "2", "--chunk", "8",
                        "--out", str(out)])
    finally:
        sys.path.remove(str(bench_dir))
    rec = json.loads(out.read_text())
    assert rec["bench"] == "serve_lab"
    # the 2 permanently-oversized requests (ISSUE 10): served as mega
    # lanes on this 8-device harness, rejected-with-hint on one device
    over = rec["oversized"]
    assert over["count"] == 2
    if over["expected"] == "mega":
        exp_ok, exp_rej = 6 + 2, 0
        assert over["statuses"] == ["ok"] * 4
    else:
        exp_ok, exp_rej = 6, 2
        assert over["hint_present"] is True
    for side in ("engine", "engine_sync"):
        assert rec[side]["ok"] == exp_ok
        assert rec[side]["rejected"] == exp_rej
        assert rec[side]["bit_identical_sample"] is True
        assert rec[side]["boundary_wait_s"] >= 0
        assert "device_idle_frac_est" in rec[side]
    assert rec["engine"]["dispatch_depth"] == 2
    assert rec["engine_sync"]["dispatch_depth"] == 0
    assert rec["dispatch_ab_speedup"] is not None
    assert rec["one_compile_per_bucket_lane_tier"] is True


def test_serve_frontend_lab_harness_smoke(tmp_path):
    """The front-end lab harness (offline policy-layer drain + online
    Poisson EDF-vs-FIFO A/B) runs end-to-end on a tiny 8-request load
    and emits every field the committed artifact relies on. Timing
    thresholds deliberately NOT asserted beyond the structural EDF >=
    FIFO invariant the lab itself enforces."""
    import importlib.util
    import sys
    from pathlib import Path

    bench_dir = Path(__file__).resolve().parent.parent / "benchmarks"
    sys.path.insert(0, str(bench_dir))
    try:
        spec = importlib.util.spec_from_file_location(
            "serve_frontend_lab_smoke", bench_dir / "serve_frontend_lab.py")
        lab = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(lab)
        out = tmp_path / "serve_frontend_lab.json"
        rc = lab.main(["--requests", "8", "--lanes", "2", "--chunk", "8",
                       "--out", str(out)])
    finally:
        sys.path.remove(str(bench_dir))
    rec = json.loads(out.read_text())
    assert rc == 0
    assert rec["bench"] == "serve_frontend_lab"
    assert rec["offline_drain"]["ok"] == 8
    # small runs skip the committed-baseline compare (population differs)
    assert rec["offline_drain"]["vs_serve_lab_engine"] is None
    for side in ("online_fifo", "online_edf"):
        blk = rec[side]
        assert sum(blk["statuses"].values()) == 8
        assert blk["deadline_carrying"] == 4
        assert blk["deadline_hit_rate"] is not None
        assert "latency_quantiles_s" in blk
    assert (rec["online_edf"]["deadline_hit_rate"]
            >= rec["online_fifo"]["deadline_hit_rate"])


def test_serve_cli_missing_file(tmp_cwd, capsys):
    from heat_tpu.cli import main

    rc = main(["serve", "--requests", "nope.jsonl"])
    assert rc == 2
    assert "not found" in capsys.readouterr().err


def test_serve_cli_requires_requests_or_listen(capsys):
    from heat_tpu.cli import main

    rc = main(["serve"])
    assert rc == 2
    assert "--listen" in capsys.readouterr().err


# --- per-lane fault domains (ISSUE 5) ---------------------------------------


def _run_wave(tmp_path, tag, cfgs, **kw):
    """Drain one wave through a fresh engine writing <tag>/ npz files;
    returns (engine, {id: record})."""
    faults.reset()
    out = tmp_path / tag
    eng = Engine(quiet(lanes=2, chunk=8, buckets=(32,), out_dir=str(out),
                       keep_fields=True, **kw))
    ids = [eng.submit(cfg, request_id=f"r{i}")
           for i, cfg in enumerate(cfgs)]
    recs = {r["id"]: r for r in eng.results()}
    return eng, recs, out, ids


QUAR_WAVE = [HeatConfig(n=16, ntime=40, dtype="float64"),
             HeatConfig(n=24, ntime=56, dtype="float64", ic="hat_small"),
             HeatConfig(n=16, ntime=40, dtype="float64", nu=0.1),
             HeatConfig(n=20, ntime=24, dtype="float64", bc="ghost",
                        ic="uniform")]


@pytest.mark.parametrize("depth", [0, 2])
def test_lane_nan_quarantine_isolates_poisoned_lane(tmp_path, depth):
    """Acceptance: a lane-nan-poisoned lane fails ONLY its own record
    (structured nonfinite status + approximate step) while every
    co-scheduled lane's .npz output stays bit-identical to a clean run —
    at dispatch depths 0 (sync fallback) and 2 (pipelined)."""
    _, clean, _, _ = _run_wave(tmp_path, f"clean{depth}", QUAR_WAVE,
                               dispatch_depth=depth)
    eng, chaos, out, _ = _run_wave(tmp_path, f"chaos{depth}", QUAR_WAVE,
                                   dispatch_depth=depth,
                                   inject="lane-nan@10:req=r1")
    bad = chaos["r1"]
    assert bad["status"] == "nonfinite"
    assert "non-finite field detected at ~step" in bad["error"]
    assert not (out / "r1.npz").exists()  # a NaN field never persists
    assert eng.lanes_quarantined == 1 and eng.summary()["nonfinite"] == 1
    for rid in ("r0", "r2", "r3"):
        assert chaos[rid]["status"] == "ok"
        with np.load(out / f"{rid}.npz") as z:
            np.testing.assert_array_equal(z["T"], clean[rid]["T"])
    # and the healthy lanes equal their solo runs too (not just clean-run
    # equal: the quarantine must not perturb the masking contract)
    np.testing.assert_array_equal(chaos["r0"]["T"], solve(QUAR_WAVE[0]).T)


@pytest.mark.parametrize("depth", [0, 2])
def test_serve_on_nan_rollback_recovers_transient_poison(tmp_path, depth):
    """Acceptance: --serve-on-nan rollback restores the flagged lane's
    last verified-finite boundary and re-steps it ALONE; the lane-nan
    injection fires once per request, so the re-step is clean and the
    final field is bit-identical to an unpoisoned run."""
    eng, recs, _, _ = _run_wave(tmp_path, f"rb{depth}", QUAR_WAVE,
                                dispatch_depth=depth, on_nan="rollback",
                                inject="lane-nan@10:req=r1")
    assert eng.rollbacks == 1 and eng.lanes_quarantined == 0
    for i, cfg in enumerate(QUAR_WAVE):
        assert recs[f"r{i}"]["status"] == "ok", recs[f"r{i}"]
        np.testing.assert_array_equal(recs[f"r{i}"]["T"], solve(cfg).T)


def test_rollback_deterministic_blowup_quarantines_after_budget(tmp_path):
    """A genuinely unstable request (sigma far past the FTCS bound)
    re-flags after every rollback: the bounded retry budget must declare
    it deterministic and quarantine it, while its lane-mate finishes."""
    cfgs = [HeatConfig(n=16, ntime=200, dtype="float32", sigma=9.0),
            HeatConfig(n=16, ntime=40, dtype="float32")]
    eng, recs, _, _ = _run_wave(tmp_path, "boom", cfgs, on_nan="rollback")
    assert recs["r0"]["status"] == "nonfinite"
    assert "after 2 rollbacks (deterministic blow-up)" in recs["r0"]["error"]
    assert eng.rollbacks == 2 and eng.lanes_quarantined == 1
    assert recs["r1"]["status"] == "ok"
    np.testing.assert_array_equal(
        np.asarray(recs["r1"]["T"], np.float32),
        np.asarray(solve(cfgs[1]).T, np.float32))


def test_boundary_vector_carries_per_lane_finite_bits():
    """Engine-level unit: the chunk program's (K_BOUNDARY, L) boundary
    vector flags exactly the poisoned lane — no extra D2H beyond the
    boundary fetch the scheduler already pays. Rows 2+ carry the
    bitcast numerics stats (ISSUE 15); rows 0-1 stay the int32
    remaining/finite contract this test pins."""
    key = BucketKey(2, 16, "float64", "edges")
    eng = LaneEngine(key, 2, 4)
    from heat_tpu.grid import initial_condition

    cfg = HeatConfig(n=16, ntime=8, dtype="float64")
    for lane in (0, 1):
        eng.load_lane(lane, initial_condition(cfg), cfg.r, 8, cfg.bc_value)
    eng.poison_lane(0, cfg.n)
    b = eng.step_chunk()
    assert b.shape == (engine_mod.K_BOUNDARY, 2)
    assert list(b[0]) == [4, 4]        # remaining: both stepped the chunk
    assert list(b[1]) == [0, 1]        # finite bits: only lane 0 flagged


def test_deadline_sheds_queued_request_without_admitting():
    """A queued request already past its deadline is shed at admission
    time (status deadline, 'never admitted') — it must not occupy a lane
    for a result nobody is waiting for. The lane-holder is unaffected."""
    eng = Engine(quiet(lanes=1, chunk=8, buckets=(16,)))
    slow = eng.submit(HeatConfig(n=16, ntime=40, dtype="float64"))
    doomed = eng.submit(HeatConfig(n=16, ntime=40, dtype="float64"),
                        deadline_ms=1e-6)
    recs = {r["id"]: r for r in eng.results()}
    assert recs[slow]["status"] == "ok"
    assert recs[doomed]["status"] == "deadline"
    assert "never admitted" in recs[doomed]["error"]
    assert eng.deadline_misses == 1


def test_deadline_preempts_running_lane_at_boundary(monkeypatch):
    """A lane whose request blows its deadline mid-flight is preempted at
    its NEXT chunk boundary (status deadline, approximate step count) and
    the freed lane admits the next queued request. Driven by a fake wall
    clock (1 s per reading) so the preemption step is deterministic-ish
    and the test never sleeps."""
    import heat_tpu.serve.scheduler as sched_mod

    t = {"now": 0.0}

    def fake_clock():
        t["now"] += 1.0
        return t["now"]

    monkeypatch.setattr(sched_mod, "wall_clock", fake_clock)
    eng = Engine(quiet(lanes=1, chunk=8, buckets=(16,)))
    # 20 s budget: survives admission (a few clock reads) but not the
    # per-boundary reads of a 10-chunk solve
    doomed = eng.submit(HeatConfig(n=16, ntime=80, dtype="float64"),
                        deadline_ms=20_000.0)
    follower = eng.submit(HeatConfig(n=16, ntime=8, dtype="float64"))
    recs = {r["id"]: r for r in eng.results()}
    assert recs[doomed]["status"] == "deadline"
    assert "preempted at the chunk boundary" in recs[doomed]["error"]
    assert recs[follower]["status"] == "ok"  # freed lane kept serving
    assert eng.deadline_misses == 1


def test_engine_default_deadline_and_per_request_override():
    """ServeConfig.deadline_ms is the engine default; a request's own
    deadline_ms overrides it in both directions."""
    eng = Engine(quiet(lanes=2, chunk=4, buckets=(16,), deadline_ms=1e-6))
    doomed = eng.submit(HeatConfig(n=8, ntime=4, dtype="float64"))
    saved = eng.submit(HeatConfig(n=8, ntime=4, dtype="float64"),
                       deadline_ms=120_000.0)
    recs = {r["id"]: r for r in eng.results()}
    assert recs[doomed]["status"] == "deadline"
    assert recs[saved]["status"] == "ok"
    assert recs[saved]["deadline_ms"] == 120_000.0


def test_max_queue_sheds_with_structured_overloaded_rejection():
    eng = Engine(quiet(lanes=1, chunk=4, buckets=(16,), max_queue=2))
    rids = [eng.submit(HeatConfig(n=8, ntime=4, dtype="float64"))
            for _ in range(5)]
    recs = {r["id"]: r for r in eng.results()}
    statuses = [recs[r]["status"] for r in rids]
    assert statuses == ["ok", "ok", "rejected", "rejected", "rejected"]
    for r in rids[2:]:
        assert "overloaded" in recs[r]["error"]
    assert eng.shed == 3 and eng.summary()["shed"] == 3
    # the shed requests never held a lane or a queue slot: a later wave
    # still serves (the guardrail protects the engine, not one batch)
    again = eng.submit(HeatConfig(n=8, ntime=4, dtype="float64"))
    assert {r["id"]: r for r in eng.results()}[again]["status"] == "ok"


def test_fetch_watchdog_fails_group_cleanly_others_drain(tmp_path):
    """Acceptance: a fetch-hang beyond the watchdog fails the affected
    GROUP's requests with structured records — and the engine still
    returns a record for every request, with other bucket groups
    draining normally (no hang)."""
    faults.reset()
    eng = Engine(quiet(lanes=2, chunk=8, buckets=(16,),
                       inject="fetch-hang:ms=1500", fetch_timeout_s=0.2,
                       flight_dir=str(tmp_path)))
    # f64 group submitted first -> its boundary fetch comes first -> hangs
    hung = [eng.submit(HeatConfig(n=16, ntime=24, dtype="float64"))
            for _ in range(3)]
    fine = [eng.submit(HeatConfig(n=16, ntime=24, dtype="float32"))
            for _ in range(2)]
    recs = {r["id"]: r for r in eng.results()}
    assert len(recs) == 5  # a record for EVERY request — nothing dropped
    for rid in hung:
        assert recs[rid]["status"] == "error"
        assert "fetch-watchdog" in recs[rid]["error"]
    for rid in fine:
        assert recs[rid]["status"] == "ok"
    assert eng.watchdog_fired == 1


def test_fetch_watchdog_fires_in_sync_fallback_too(tmp_path):
    faults.reset()
    eng = Engine(quiet(lanes=1, chunk=8, buckets=(16,), dispatch_depth=0,
                       inject="fetch-hang:ms=1500", fetch_timeout_s=0.2,
                       flight_dir=str(tmp_path)))
    rid = eng.submit(HeatConfig(n=16, ntime=24, dtype="float64"))
    recs = {r["id"]: r for r in eng.results()}
    assert recs[rid]["status"] == "error"
    assert "fetch-watchdog" in recs[rid]["error"]


def test_drain_on_exception_no_orphan_tmp_and_error_surfaces(tmp_path):
    """Satellite: an exception mid-Engine.run must still drain the
    SnapshotWriter — the already-finished request's file lands, no
    orphan *.tmp remains, and the scheduler's error (not a writer
    artifact) is what propagates."""
    out = tmp_path / "results"
    eng = Engine(quiet(lanes=1, chunk=4, buckets=(16,), out_dir=str(out)))
    done = eng.submit(HeatConfig(n=16, ntime=4, dtype="float64"))
    eng.submit(HeatConfig(n=16, ntime=400, dtype="float64"))
    real = LaneEngine.dispatch_chunk
    calls = {"n": 0}

    def flaky_dispatch(self, k=None):
        calls["n"] += 1
        if calls["n"] > 4:
            raise RuntimeError("chaos: device fell over mid-run")
        return real(self, k)

    try:
        LaneEngine.dispatch_chunk = flaky_dispatch
        with pytest.raises(RuntimeError, match="device fell over"):
            eng.run()
    finally:
        LaneEngine.dispatch_chunk = real
    recs = {r["id"]: r for r in eng._records}
    assert recs[done]["status"] == "ok"
    assert (out / f"{done}.npz").exists()      # writer drained, not killed
    assert not list(out.glob("*.tmp"))         # no torn temp files


def test_serve_config_validates_fault_domain_knobs():
    with pytest.raises(ValueError, match="on_nan"):
        ServeConfig(on_nan="retry")
    with pytest.raises(ValueError, match="deadline_ms"):
        ServeConfig(deadline_ms=0)
    with pytest.raises(ValueError, match="max_queue"):
        ServeConfig(max_queue=-1)
    with pytest.raises(ValueError, match="fetch_timeout_s"):
        ServeConfig(fetch_timeout_s=0)
    with pytest.raises(ValueError, match="unknown fault kind"):
        ServeConfig(inject="bogus@3")
    eng = Engine(quiet())
    with pytest.raises(ValueError, match="deadline_ms"):
        eng.submit(HeatConfig(n=8, ntime=1), deadline_ms=-5)


def test_summary_and_timing_surface_fault_domain_counters(tmp_path):
    eng, _, _, _ = _run_wave(tmp_path, "counters", QUAR_WAVE,
                             inject="lane-nan@10:req=r1")
    s = eng.summary()
    assert s["lanes_quarantined"] == 1
    assert set(s) >= {"rollbacks", "deadline_misses", "shed",
                      "watchdog_fired"}
    assert eng.timing.lanes_quarantined == 1
    assert any("serve faults" in l for l in eng.timing.report_lines())


def test_config_from_request_accepts_scheduler_keys():
    cfg = config_from_request({"id": "x", "n": 16, "ntime": 4,
                               "deadline_ms": 2000})
    assert cfg.n == 16  # deadline_ms is the scheduler's, not physics


def test_serve_jsonl_deadline_ms_field(tmp_path):
    from heat_tpu.serve.api import load_requests

    p = tmp_path / "reqs.jsonl"
    p.write_text('{"id": "a", "n": 16, "ntime": 4, "deadline_ms": 2000}\n'
                 '{"id": "b", "n": 16, "ntime": 4}\n'
                 '{"id": "c", "n": 16, "ntime": 4, "deadline_ms": -3}\n')
    rows = load_requests(p)
    assert rows[0].id == "a" and rows[0].deadline_ms == 2000.0
    assert rows[0].error is None
    assert rows[1].deadline_ms is None
    assert rows[2].cfg is None and "deadline_ms" in rows[2].error


def test_serve_jsonl_tenant_and_class_fields(tmp_path):
    """tenant/class are scheduler fields validated in config.py: good
    values parse through, a typoed class is a per-line rejection."""
    from heat_tpu.serve.api import load_requests

    p = tmp_path / "reqs.jsonl"
    p.write_text(
        '{"id": "a", "n": 16, "ntime": 4, "tenant": "acme", '
        '"class": "interactive"}\n'
        '{"id": "b", "n": 16, "ntime": 4}\n'
        '{"id": "c", "n": 16, "ntime": 4, "class": "premium"}\n'
        '{"id": "d", "n": 16, "ntime": 4, "tenant": "bad tenant!"}\n')
    rows = load_requests(p)
    assert rows[0].tenant == "acme" and rows[0].slo_class == "interactive"
    assert rows[1].tenant == "default" and rows[1].slo_class == "standard"
    assert rows[2].cfg is None and "class" in rows[2].error
    assert rows[3].cfg is None and "tenant" in rows[3].error


def test_serve_cli_fault_domain_flags(tmp_cwd, capsys):
    from heat_tpu.cli import main

    reqs = tmp_cwd / "reqs.jsonl"
    reqs.write_text(
        '{"id": "good", "n": 16, "ntime": 24, "dtype": "float64"}\n'
        '{"id": "bad", "n": 16, "ntime": 24, "dtype": "float64"}\n')
    rc = main(["serve", "--requests", "reqs.jsonl", "--buckets", "16",
               "--chunk", "8", "--inject", "lane-nan@10:req=bad",
               "--json"])
    out = capsys.readouterr().out
    assert rc == 1  # a quarantined request is a nonzero exit
    records = {r["id"]: r for r in
               (json.loads(l) for l in out.splitlines()
                if l.startswith("{") and '"serve_request"' in l)}
    assert records["bad"]["status"] == "nonfinite"
    assert records["good"]["status"] == "ok"
    assert "fault domains: 1 quarantined" in out
    summary = json.loads([l for l in out.splitlines()
                          if '"lanes_quarantined"' in l][-1])
    assert summary["lanes_quarantined"] == 1
    # rollback mode recovers the same wave (lane-nan fires once/request)
    rc = main(["serve", "--requests", "reqs.jsonl", "--buckets", "16",
               "--chunk", "8", "--inject", "lane-nan@10:req=bad",
               "--serve-on-nan", "rollback"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "2 ok" in out and "1 rollback(s)" in out


def test_serve_cli_max_queue_and_deadline(tmp_cwd, capsys):
    from heat_tpu.cli import main

    reqs = tmp_cwd / "reqs.jsonl"
    reqs.write_text("".join(
        f'{{"id": "r{i}", "n": 16, "ntime": 8, "dtype": "float64"}}\n'
        for i in range(4)))
    rc = main(["serve", "--requests", "reqs.jsonl", "--buckets", "16",
               "--chunk", "4", "--max-queue", "2"])
    out = capsys.readouterr().out
    assert rc == 1
    assert "2 ok" in out and "2 rejected" in out and "2 shed" in out
    # a sub-millisecond engine-default deadline sheds everything
    rc = main(["serve", "--requests", "reqs.jsonl", "--buckets", "16",
               "--chunk", "4", "--serve-deadline", "0.0001"])
    out = capsys.readouterr().out
    assert rc == 1
    assert "4 deadline miss(es)" in out
