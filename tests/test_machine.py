"""Per-chip machine model (heat_tpu.machine): classification, override,
planner re-planning, and roofline labeling.

VERDICT r3 weak #5: planner/roofline constants were v5e literals baked
into source — on a v5p the planners would pick measurably wrong geometry
and vs_baseline would silently divide by the wrong chip's roofline.
These tests pin the fix: device-kind selection, cache flushing on
override, and that a mocked v5p actually changes planner output.
"""

import pytest

from heat_tpu import machine
from heat_tpu.ops.pallas_stencil import _plan_2d, _plan_3d


@pytest.fixture(autouse=True)
def _restore_override():
    yield
    machine.override(None)


@pytest.mark.parametrize("kind,expect", [
    ("TPU v5 lite", "v5e"),
    ("TPU v5e", "v5e"),
    ("TPU v5", "v5p"),
    ("TPU v5p", "v5p"),
    ("TPU v4", "v4"),
    ("TPU v6 lite", "v6e"),
    ("TPU v6e", "v6e"),
    ("cpu", "v5e"),          # unknown kinds fall back to the v5e table
    ("Strange Chip 9", "v5e"),
])
def test_classify_device_kind_spellings(kind, expect):
    assert machine.classify(kind).name == expect


def test_v5e_is_the_only_calibrated_entry():
    assert machine.classify("TPU v5e").calibrated
    for kind in ("TPU v4", "TPU v5p", "TPU v6e", "cpu"):
        chip = machine.classify(kind)
        assert not chip.calibrated, kind
        assert "(uncalibrated)" in chip.label, kind


def test_roofline_denominators():
    v5e = machine.classify("TPU v5e")
    # 819e9/8 = 1.02375e11 (BASELINE.md rounds it to 1.024e11)
    assert v5e.roofline_points_per_s("float32") == pytest.approx(
        1.024e11, rel=1e-3)
    assert v5e.roofline_points_per_s("bfloat16") == pytest.approx(
        2.048e11, rel=1e-3)
    v5p = machine.classify("TPU v5p")
    # the round-3 verdict's "silently ~3.4x pessimistic" scenario
    assert v5p.roofline_points_per_s("float32") / \
        v5e.roofline_points_per_s("float32") == pytest.approx(2765 / 819)


def test_override_changes_current_and_flushes_planner_caches():
    base = machine.current().name
    _plan_2d((4096, 4096), "float32", 32)  # populate
    assert _plan_2d.cache_info().currsize >= 1
    machine.override("TPU v5p")
    assert machine.current().name == "v5p"
    assert _plan_2d.cache_info().currsize == 0  # flushed
    machine.override(None)
    assert machine.current().name == base


def test_planner_picks_different_geometry_on_v5p():
    """The load-bearing property: the SAME shape gets a different plan on
    a chip with a different compute/bandwidth balance. At 1024^3 the v5e
    table picks a deeper fuse (bandwidth-starved); the v5p's 3.38x HBM
    (vs 2.33x compute) shifts the additive cost model to shallower k."""
    shape = (1024, 1024, 1024)
    machine.override("TPU v5e")
    plan_e = _plan_3d(shape, "float32", 8)
    machine.override("TPU v5p")
    plan_p = _plan_3d(shape, "float32", 8)
    assert plan_e != plan_p, (plan_e, plan_p)
    k_e, k_p = plan_e[3], plan_p[3]
    assert k_p <= k_e  # more bandwidth => no deeper fusion needed


def test_headline_record_labels_baseline_chip():
    from heat_tpu import benchmark

    rec = benchmark.headline_measure(n=128, steps=8, repeats=1)
    assert rec["baseline_chip"].startswith(machine.current().name)
    assert rec["vs_baseline"] == pytest.approx(
        rec["value"] / machine.current().roofline_points_per_s("float32"))


def test_v4_small_vmem_table_plans_small_bands():
    """v4 has 16 MiB VMEM/core, not v5e's 128 — the AOT compile validator
    (benchmarks/topology_validate.py) caught the original spec table's
    110 MiB assumption with a real RESOURCE_EXHAUSTED verdict. The v4
    entry must keep every planned band inside the real VMEM."""
    machine.override("TPU v4")
    chip = machine.current()
    assert chip.vmem_limit_bytes <= 14 * 1024 * 1024
    plan = _plan_2d((4096, 4096), "float32", 32)
    assert plan[0] == "coltiled"
    _, R, C, kr, kc, k = plan
    band = (R + 2 * kr) * (C + 2 * kc) * 4  # accumulation dtype
    assert band <= chip.vmem_fit_bytes
    plan3 = _plan_3d((512, 512, 512), "float32", 8)
    assert plan3 is not None  # a plan still exists under 16 MiB
