"""dat-file contract, native fast path, per-shard output, checkpointing."""

import numpy as np
import pytest

from heat_tpu.backends import solve
from heat_tpu.config import HeatConfig
from heat_tpu.grid import coords, initial_condition
from heat_tpu.io import read_dat, write_dat, write_soln_sharded
from heat_tpu.io.native import fast_read_values, fast_write_triplets, native_available
from heat_tpu.parallel.mesh import build_mesh
from heat_tpu.runtime import checkpoint


def test_write_read_roundtrip(tmp_path):
    cfg = HeatConfig(n=9, dtype="float64")
    T = initial_condition(cfg) * 1.234567890123
    axes = coords(cfg)
    p = tmp_path / "soln.dat"
    write_dat(p, axes, T)
    axes2, T2 = read_dat(p)
    np.testing.assert_allclose(T2, T, rtol=0, atol=1e-15)
    np.testing.assert_allclose(axes2[0], axes[0], atol=1e-15)
    # layout parity: line i*n+j holds x[i] y[j] T[i,j] (serial/heat.f90:77-83)
    first = p.read_text().splitlines()[0].split()
    assert float(first[0]) == 0.0 and float(first[1]) == 0.0


def test_file_is_regex_splittable(tmp_path):
    """The reference's out.py parses lines via re.split on whitespace
    (fortran/serial/out.py:17-25); our files must stay compatible."""
    import re

    cfg = HeatConfig(n=5, dtype="float64")
    p = tmp_path / "soln.dat"
    write_dat(p, coords(cfg), initial_condition(cfg))
    lines = p.read_text().strip().splitlines()
    assert len(lines) == 25
    for ln in lines[:3]:
        vals = [v for v in re.split(r"\s+", ln.strip()) if v]
        assert len(vals) == 3
        [float(v) for v in vals]


def test_native_fastio(tmp_path):
    if not native_available():
        pytest.skip("g++/make unavailable; numpy fallback covers correctness")
    table = np.random.rand(100, 3)
    p = tmp_path / "fast.dat"
    assert fast_write_triplets(str(p), table)
    vals = fast_read_values(str(p), 300)
    np.testing.assert_allclose(vals.reshape(100, 3), table, atol=1e-15)


def test_native_and_numpy_paths_agree(tmp_path):
    if not native_available():
        pytest.skip("native lib unavailable")
    table = np.random.rand(50, 3)
    pn = tmp_path / "n.dat"
    pf = tmp_path / "f.dat"
    with open(pn, "w") as f:
        np.savetxt(f, table, fmt="%.17g")
    assert fast_write_triplets(str(pf), table)
    np.testing.assert_allclose(np.loadtxt(pf), np.loadtxt(pn), rtol=0, atol=0)


def test_write_soln_sharded(tmp_path):
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    cfg = HeatConfig(n=16, dtype="float64")
    mesh = build_mesh(2, (2, 2))
    T = jnp.asarray(initial_condition(cfg))
    Ts = jax.device_put(T, NamedSharding(mesh, P("x", "y")))
    files = write_soln_sharded(tmp_path, coords(cfg), Ts, mesh)
    assert len(files) == 4
    assert sorted(f.name for f in files) == [
        "soln00000.dat", "soln00001.dat", "soln00002.dat", "soln00003.dat",
    ]
    # reassemble and compare
    blocks = {}
    for f in files:
        axes, blk = read_dat(f)
        blocks[f.name] = (axes, blk)
    top = np.hstack([blocks["soln00000.dat"][1], blocks["soln00001.dat"][1]])
    bot = np.hstack([blocks["soln00002.dat"][1], blocks["soln00003.dat"][1]])
    np.testing.assert_array_equal(np.vstack([top, bot]), np.asarray(T))
    # per-shard coords are the global slice (mpi+cuda/heat.F90:123-138)
    axes0, _ = blocks["soln00003.dat"]
    assert axes0[0][0] == pytest.approx(coords(cfg)[0][8])


def test_checkpoint_resume_equivalence(tmp_cwd):
    """Interrupt-and-resume == uninterrupted run (extension over the
    reference, which has no mid-run persistence; SURVEY.md §5)."""
    cfg = HeatConfig(n=24, ntime=10, dtype="float64", backend="xla",
                     checkpoint_every=5, checkpoint_dir=str(tmp_cwd / "ck"))
    # run only to step 5 (simulated interrupt)
    solve(cfg.with_(ntime=5))
    assert checkpoint.latest(cfg) is not None
    # resume picks up at 5 and finishes to 10
    resumed = solve(cfg)
    assert resumed.start_step == 5
    direct = solve(cfg.with_(checkpoint_every=0, ntime=10))
    np.testing.assert_allclose(resumed.T, direct.T, rtol=0, atol=0)


def test_torn_checkpoint_tmp_is_ignored(tmp_cwd):
    """A crash mid-save leaves a .tmp file; resume must skip it."""
    cfg = HeatConfig(n=16, ntime=4, backend="serial", dtype="float64",
                     checkpoint_every=2, checkpoint_dir=str(tmp_cwd / "ck"))
    solve(cfg)
    good = checkpoint.latest(cfg)
    torn = good.parent / (good.name.replace("00000004", "00000006") + ".tmp")
    torn.write_bytes(b"partial garbage")
    assert checkpoint.latest(cfg) == good  # glob must not match *.tmp
    T, step = checkpoint.load(checkpoint.latest(cfg), cfg)
    assert step == 4


def test_resume_ignores_checkpoints_beyond_ntime(tmp_cwd):
    """Re-running with a smaller ntime must not 'resume' from the future."""
    cfg = HeatConfig(n=16, ntime=10, dtype="float64", backend="xla",
                     checkpoint_every=5, checkpoint_dir=str(tmp_cwd / "ck"))
    solve(cfg)  # saves steps 5 and 10
    short = solve(cfg.with_(ntime=3))
    assert short.start_step == 0 and short.timing.steps == 3
    fresh = solve(cfg.with_(ntime=3, checkpoint_every=0))
    np.testing.assert_array_equal(short.T, fresh.T)
    # ntime=5 may legitimately reuse the step-5 checkpoint verbatim
    at5 = solve(cfg.with_(ntime=5))
    assert at5.start_step == 5 and at5.timing.steps == 0


def test_gsum_identical_across_backends_f32():
    cfg = HeatConfig(n=64, ntime=10, dtype="float32", report_sum=True)
    sums = {b: solve(cfg.with_(backend=b)).gsum
            for b in ("serial", "xla", "pallas")}
    assert sums["serial"] == sums["xla"] == sums["pallas"]


def test_checkpoint_rejects_mismatched_config(tmp_cwd):
    cfg = HeatConfig(n=16, ntime=4, backend="serial", dtype="float64",
                     checkpoint_every=2, checkpoint_dir=str(tmp_cwd / "ck"))
    solve(cfg)
    bad = cfg.with_(nu=0.99)
    with pytest.raises(ValueError):
        checkpoint.load(checkpoint.latest(bad), bad)
