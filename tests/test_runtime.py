"""Debug/observability subsystems: numerics checking, profiling, dist init,
timing report."""

import numpy as np
import pytest

from heat_tpu.backends import solve
from heat_tpu.config import HeatConfig
from heat_tpu.runtime.debug import check_finite, maybe_profile
from heat_tpu.runtime.timing import Timing


def test_check_finite_passes_on_good_field():
    check_finite(np.ones((4, 4)), step=3)


def test_check_finite_raises_with_step_context():
    bad = np.ones((4, 4))
    bad[2, 2] = np.nan
    with pytest.raises(FloatingPointError, match="step 7"):
        check_finite(bad, step=7)


def test_unstable_sigma_detected_by_check_numerics():
    """sigma far above the FTCS bound blows up; debug mode names the step."""
    cfg = HeatConfig(n=32, ntime=200, sigma=2.0, dtype="float32",
                     backend="xla", check_numerics=True, heartbeat_every=10)
    with pytest.raises(FloatingPointError):
        solve(cfg)
    # serial backend path too (checks at end)
    with pytest.raises(FloatingPointError):
        solve(cfg.with_(backend="serial", heartbeat_every=0))


def test_stable_run_with_check_numerics_is_clean():
    cfg = HeatConfig(n=32, ntime=20, dtype="float32", backend="xla",
                     check_numerics=True)
    res = solve(cfg)
    assert np.isfinite(res.T).all()


def test_maybe_profile_writes_trace(tmp_path):
    import jax
    import jax.numpy as jnp

    with maybe_profile(str(tmp_path / "trace")):
        jax.block_until_ready(jnp.ones((8, 8)) * 2)
    assert any((tmp_path / "trace").rglob("*"))


def test_maybe_profile_none_is_noop():
    with maybe_profile(None):
        pass


def test_profile_flag_through_solve(tmp_path):
    cfg = HeatConfig(n=32, ntime=4, dtype="float32", backend="xla",
                     profile_dir=str(tmp_path / "prof"))
    solve(cfg)
    assert any((tmp_path / "prof").rglob("*"))


def test_init_distributed_single_process_noop():
    from heat_tpu.parallel.dist import init_distributed, is_master

    init_distributed()  # CPU, no coordinator: must be a clean no-op
    assert is_master()


def test_timing_report_lines():
    t = Timing(total_s=2.0, compile_s=0.5, solve_s=1.0, steps=10, points=100)
    lines = t.report_lines()
    assert lines[0] == "simulation completed!!!!"
    assert any("Average time per timestep: 0.1" in l for l in lines)
    assert t.points_per_s == pytest.approx(1000.0)


def test_two_point_rate_cancels_fixed_overhead(monkeypatch):
    """The shared measurement protocol (bench.py + kernel lab): with a
    fixed per-measurement overhead riding on the sync fence and compute
    time C per call, the corrected rate must be ~work/C (not work/(C+O)),
    and must fall back to the raw rate when overhead dominates (noise
    floor) rather than report an inflated figure."""
    import time as _time

    from heat_tpu.runtime import timing as timing_mod

    monkeypatch.setattr(timing_mod, "sync",
                        lambda x: (_time.sleep(0.060), x)[1])

    # compute 30 ms/call + 60 ms overhead/measurement:
    # T1 ~ 90 ms, T2 ~ 120 ms -> corrected ~ work/30ms, raw ~ work/90ms
    res = timing_mod.two_point_rate(
        lambda x: (_time.sleep(0.030), x)[1], "x", work=1.0, repeats=2)
    corrected, raw = res
    assert raw == pytest.approx(1.0 / 0.090, rel=0.25)
    assert corrected == pytest.approx(1.0 / 0.030, rel=0.25)
    assert res.fell_back is False

    # overhead-dominated (compute 1 ms vs 60 ms overhead): the noise
    # floor must return the raw rate unchanged AND flag the fallback
    # explicitly (calibrate refuses to trust a fallen-back HBM rate;
    # float-equality re-derivation was review-flagged as fragile)
    res2 = timing_mod.two_point_rate(
        lambda x: (_time.sleep(0.001), x)[1], "x", work=1.0, repeats=2)
    corrected2, raw2 = res2
    assert corrected2 == raw2
    assert res2.fell_back is True

    # the result must survive pickle/copy (tuple subclass with a custom
    # __new__ needs __getnewargs__; guard_probe already ships pickles
    # across processes)
    import copy
    import pickle

    back = pickle.loads(pickle.dumps(res2))
    assert tuple(back) == tuple(res2) and back.fell_back is True
    assert copy.copy(res2).fell_back is True


def test_two_point_repeats_through_solve():
    """VERDICT r2 #9: the solve path can measure the overhead-corrected
    two-point rate alongside the raw one — on a COPY, so the solve result
    is bit-identical to a plain run."""
    cfg = HeatConfig(n=32, ntime=8, dtype="float64", backend="xla")
    plain = solve(cfg)
    timed = solve(cfg, two_point_repeats=1)
    np.testing.assert_array_equal(plain.T, timed.T)
    assert plain.timing.points_per_s_two_point is None
    assert plain.timing.two_point_fell_back is None
    assert timed.timing.points_per_s_two_point > 0
    # when the protocol ran, the fallback verdict must be recorded either
    # way — calibrate refuses to fit overhead-dominated rates (review r5)
    assert timed.timing.two_point_fell_back in (True, False)


def test_two_point_repeats_sharded_padded_carry():
    cfg = HeatConfig(n=16, ntime=4, dtype="float64", backend="sharded",
                     mesh_shape=(2, 2))
    res = solve(cfg, two_point_repeats=1)
    assert res.timing.points_per_s_two_point > 0
    ref = solve(cfg.with_(backend="serial", mesh_shape=None))
    np.testing.assert_array_equal(res.T, ref.T)
