"""Debug/observability subsystems: numerics checking, profiling, dist init,
timing report."""

import numpy as np
import pytest

from heat_tpu.backends import solve
from heat_tpu.config import HeatConfig
from heat_tpu.runtime.debug import check_finite, maybe_profile
from heat_tpu.runtime.timing import Timing


def test_check_finite_passes_on_good_field():
    check_finite(np.ones((4, 4)), step=3)


def test_check_finite_raises_with_step_context():
    bad = np.ones((4, 4))
    bad[2, 2] = np.nan
    with pytest.raises(FloatingPointError, match="step 7"):
        check_finite(bad, step=7)


@pytest.mark.filterwarnings("ignore:overflow encountered:RuntimeWarning")
def test_unstable_sigma_detected_by_check_numerics():
    """sigma far above the FTCS bound blows up; debug mode names the step.
    (The numpy overflow RuntimeWarning on the serial path is the blow-up
    MECHANISM under test, not noise worth a dirty ``pytest -q`` —
    VERDICT r5 #8 hygiene.)"""
    cfg = HeatConfig(n=32, ntime=200, sigma=2.0, dtype="float32",
                     backend="xla", check_numerics=True, heartbeat_every=10)
    with pytest.raises(FloatingPointError):
        solve(cfg)
    # serial backend path too (checks at end)
    with pytest.raises(FloatingPointError):
        solve(cfg.with_(backend="serial", heartbeat_every=0))


def test_stable_run_with_check_numerics_is_clean():
    cfg = HeatConfig(n=32, ntime=20, dtype="float32", backend="xla",
                     check_numerics=True)
    res = solve(cfg)
    assert np.isfinite(res.T).all()


def test_maybe_profile_writes_trace(tmp_path):
    import jax
    import jax.numpy as jnp

    with maybe_profile(str(tmp_path / "trace")):
        jax.block_until_ready(jnp.ones((8, 8)) * 2)
    assert any((tmp_path / "trace").rglob("*"))


def test_maybe_profile_none_is_noop():
    with maybe_profile(None):
        pass


def test_profile_flag_through_solve(tmp_path):
    cfg = HeatConfig(n=32, ntime=4, dtype="float32", backend="xla",
                     profile_dir=str(tmp_path / "prof"))
    solve(cfg)
    assert any((tmp_path / "prof").rglob("*"))


def test_init_distributed_single_process_noop():
    from heat_tpu.parallel.dist import init_distributed, is_master

    init_distributed()  # CPU, no coordinator: must be a clean no-op
    assert is_master()


def test_timing_report_lines():
    t = Timing(total_s=2.0, compile_s=0.5, solve_s=1.0, steps=10, points=100)
    lines = t.report_lines()
    assert lines[0] == "simulation completed!!!!"
    assert any("Average time per timestep: 0.1" in l for l in lines)
    assert t.points_per_s == pytest.approx(1000.0)


def test_two_point_rate_cancels_fixed_overhead(monkeypatch):
    """The shared measurement protocol (bench.py + kernel lab): with a
    fixed per-measurement overhead riding on the sync fence and compute
    time C per call, the corrected rate must be ~work/C (not work/(C+O)),
    and must fall back to the raw rate when overhead dominates (noise
    floor) rather than report an inflated figure."""
    import time as _time

    from heat_tpu.runtime import timing as timing_mod

    monkeypatch.setattr(timing_mod, "sync",
                        lambda x: (_time.sleep(0.060), x)[1])

    # compute 30 ms/call + 60 ms overhead/measurement:
    # T1 ~ 90 ms, T2 ~ 120 ms -> corrected ~ work/30ms, raw ~ work/90ms
    res = timing_mod.two_point_rate(
        lambda x: (_time.sleep(0.030), x)[1], "x", work=1.0, repeats=2)
    corrected, raw = res
    assert raw == pytest.approx(1.0 / 0.090, rel=0.25)
    assert corrected == pytest.approx(1.0 / 0.030, rel=0.25)
    assert res.fell_back is False

    # overhead-dominated (compute 1 ms vs 60 ms overhead): the noise
    # floor must return the raw rate unchanged AND flag the fallback
    # explicitly (calibrate refuses to trust a fallen-back HBM rate;
    # float-equality re-derivation was review-flagged as fragile)
    res2 = timing_mod.two_point_rate(
        lambda x: (_time.sleep(0.001), x)[1], "x", work=1.0, repeats=2)
    corrected2, raw2 = res2
    assert corrected2 == raw2
    assert res2.fell_back is True

    # the result must survive pickle/copy (tuple subclass with a custom
    # __new__ needs __getnewargs__; guard_probe already ships pickles
    # across processes)
    import copy
    import pickle

    back = pickle.loads(pickle.dumps(res2))
    assert tuple(back) == tuple(res2) and back.fell_back is True
    assert copy.copy(res2).fell_back is True


def test_two_point_repeats_through_solve():
    """VERDICT r2 #9: the solve path can measure the overhead-corrected
    two-point rate alongside the raw one — on a COPY, so the solve result
    is bit-identical to a plain run."""
    cfg = HeatConfig(n=32, ntime=8, dtype="float64", backend="xla")
    plain = solve(cfg)
    timed = solve(cfg, two_point_repeats=1)
    np.testing.assert_array_equal(plain.T, timed.T)
    assert plain.timing.points_per_s_two_point is None
    assert plain.timing.two_point_fell_back is None
    assert timed.timing.points_per_s_two_point > 0
    # when the protocol ran, the fallback verdict must be recorded either
    # way — calibrate refuses to fit overhead-dominated rates (review r5)
    assert timed.timing.two_point_fell_back in (True, False)


def test_two_point_repeats_sharded_padded_carry():
    cfg = HeatConfig(n=16, ntime=4, dtype="float64", backend="sharded",
                     mesh_shape=(2, 2))
    res = solve(cfg, two_point_repeats=1)
    assert res.timing.points_per_s_two_point > 0
    ref = solve(cfg.with_(backend="serial", mesh_shape=None))
    np.testing.assert_array_equal(res.T, ref.T)


# --- async checkpoint/telemetry pipeline (ISSUE 1) -------------------------


def test_snapshot_writer_backpressure_is_bounded():
    """A slow sink must apply backpressure (submit blocks on a full queue)
    rather than queue snapshots unboundedly — each entry pins a full-field
    device buffer."""
    import threading
    import time as _time

    from heat_tpu.runtime.async_io import SnapshotWriter

    w = SnapshotWriter(depth=1)
    in_flight = 0
    max_in_flight = 0
    lock = threading.Lock()

    def job():
        nonlocal in_flight, max_in_flight
        with lock:
            in_flight += 1
            max_in_flight = max(max_in_flight, in_flight)
        _time.sleep(0.05)
        with lock:
            in_flight -= 1

    t0 = _time.perf_counter()
    for _ in range(4):
        w.submit(job)
    w.drain()
    wall = _time.perf_counter() - t0
    assert w.completed == 4            # nothing dropped
    assert max_in_flight == 1          # one writer thread, FIFO
    assert w.wait_s > 0.05             # submits genuinely blocked
    assert wall >= 4 * 0.05            # the sink really ran serially


def test_snapshot_writer_error_surfaces_and_later_jobs_still_run():
    import threading

    from heat_tpu.runtime.async_io import SnapshotWriter

    ran = []
    gate = threading.Event()

    def bad():
        raise OSError("disk gone")

    w = SnapshotWriter(depth=2)
    w.submit(lambda: gate.wait(5))    # hold the worker so the error can't
    w.submit(bad)                     # surface before everything is queued
    w.submit(lambda: ran.append(1))
    gate.set()
    with pytest.raises(OSError, match="disk gone"):
        w.drain()
    assert ran == [1]                 # queued-after-failure still attempted
    # the suppressed form must flush too, without raising
    w2 = SnapshotWriter()
    w2.submit(bad)
    w2.drain(raise_errors=False)


def test_async_checkpoints_bit_identical_to_sync(tmp_path):
    """The pipeline must change WHEN the write happens, never WHAT is
    written: same files, same bytes-level arrays, and a resume from an
    async-written checkpoint matches the sync path bit-for-bit."""
    from heat_tpu.runtime import checkpoint

    da, ds = tmp_path / "async", tmp_path / "sync"
    cfg = HeatConfig(n=32, ntime=20, dtype="float64", backend="xla",
                     checkpoint_every=5)
    ra = solve(cfg.with_(checkpoint_dir=str(da)))
    rs = solve(cfg.with_(checkpoint_dir=str(ds), async_io="off"))
    assert ra.timing.overlap_s is not None     # the pipeline really ran
    assert rs.timing.overlap_s is None         # and sync really didn't
    np.testing.assert_array_equal(ra.T, rs.T)
    names_a = sorted(p.name for p in da.glob("*.npz"))
    names_s = sorted(p.name for p in ds.glob("*.npz"))
    assert names_a == names_s and len(names_a) == 4
    for name in names_a:
        Ta, sa = checkpoint.load(da / name, cfg)
        Ts, ss = checkpoint.load(ds / name, cfg)
        assert sa == ss
        np.testing.assert_array_equal(Ta, Ts)
    # resume from the async-written step-20 checkpoint == sync resume
    ra2 = solve(cfg.with_(checkpoint_dir=str(da), ntime=30))
    rs2 = solve(cfg.with_(checkpoint_dir=str(ds), ntime=30, async_io="off"))
    assert ra2.start_step == rs2.start_step == 20
    np.testing.assert_array_equal(ra2.T, rs2.T)


def test_async_drain_on_exception_keeps_every_good_snapshot(tmp_path):
    """A blow-up mid-solve must still land every finite boundary snapshot
    on disk (the last good one is exactly the state a resume needs), and
    the writer's own validation must reject the non-finite one — no NaN
    field is ever persisted on either I/O path."""
    import re

    from heat_tpu.runtime import checkpoint

    d = tmp_path / "ck"
    cfg = HeatConfig(n=32, ntime=200, sigma=2.0, dtype="float32",
                     backend="xla", checkpoint_every=10,
                     checkpoint_dir=str(d), check_numerics=True)
    with pytest.raises(FloatingPointError, match="step") as ei:
        solve(cfg)
    bad_step = int(re.search(r"at step (\d+)", str(ei.value)).group(1))
    steps_on_disk = sorted(
        int(p.stem.replace("heat_step", "")) for p in d.glob("heat_step*.npz"))
    assert steps_on_disk == list(range(10, bad_step, 10))
    for s in steps_on_disk:  # drained files are whole and finite
        T, _ = checkpoint.load(d / f"heat_step{s:08d}.npz", cfg)
        assert np.isfinite(T).all()


def test_async_writer_failure_fails_the_solve(tmp_path, monkeypatch):
    """A dead sink must stop the run (at the next boundary or the final
    drain), never let it step for hours writing nothing."""
    from heat_tpu.runtime import checkpoint

    def broken(cfg, T, step):
        raise OSError("sink is dead")

    monkeypatch.setattr(checkpoint, "save", broken)
    cfg = HeatConfig(n=32, ntime=20, dtype="float32", backend="xla",
                     checkpoint_every=5, checkpoint_dir=str(tmp_path / "ck"))
    with pytest.raises(OSError, match="sink is dead"):
        solve(cfg)


def test_async_io_off_is_the_sync_path(tmp_path):
    """--async-io off must not spin up a writer (overlap telemetry absent)
    while producing the same checkpoints."""
    cfg = HeatConfig(n=24, ntime=8, dtype="float32", backend="xla",
                     checkpoint_every=4, async_io="off",
                     checkpoint_dir=str(tmp_path / "ck"))
    res = solve(cfg)
    assert res.timing.overlap_s is None
    assert len(list((tmp_path / "ck").glob("*.npz"))) == 2


def test_snapshot_writer_retries_transient_errors():
    """An EIO/ENOSPC-class sink error gets bounded in-thread retries under
    backoff before surfacing; a clean third attempt absorbs the blip
    entirely (a flaky NFS op must not abort a day-long solve)."""
    import errno

    from heat_tpu.runtime.async_io import SnapshotWriter

    calls = []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise OSError(errno.EIO, "blip")

    w = SnapshotWriter(retries=3, retry_backoff_s=0.001)
    w.submit(flaky)
    w.drain()                      # no error: the retries absorbed it
    assert len(calls) == 3
    assert w.attempts == 3 and w.completed == 1

    # budget exhausted: the transient error finally surfaces
    w2 = SnapshotWriter(retries=2, retry_backoff_s=0.001)
    w2.submit(lambda: (_ for _ in ()).throw(OSError(errno.EIO, "dead")))
    with pytest.raises(OSError, match="dead"):
        w2.drain()
    assert w2.attempts == 3        # 1 try + 2 retries


def test_snapshot_writer_non_transient_fails_fast():
    """Only the transient OSError class is retry-worthy: a ValueError (or
    an errno-less OSError) surfaces after exactly one attempt."""
    from heat_tpu.runtime.async_io import SnapshotWriter

    w = SnapshotWriter(retries=3, retry_backoff_s=0.001)
    w.submit(lambda: (_ for _ in ()).throw(ValueError("fingerprint")))
    with pytest.raises(ValueError, match="fingerprint"):
        w.drain()
    assert w.attempts == 1


def test_snapshot_writer_drain_timeout():
    """A hung sink must not wedge the exit path: drain raises TimeoutError
    at its deadline (and only logs on the suppressed exception-exit form)."""
    import threading

    from heat_tpu.runtime.async_io import SnapshotWriter

    gate = threading.Event()
    w = SnapshotWriter()
    w.submit(lambda: gate.wait(30))
    with pytest.raises(TimeoutError, match="drain"):
        w.drain(timeout_s=0.2)
    gate.set()                     # release the abandoned daemon thread

    gate2 = threading.Event()
    w2 = SnapshotWriter()
    w2.submit(lambda: gate2.wait(30))
    w2.drain(raise_errors=False, timeout_s=0.2)  # logs, must not raise
    gate2.set()


def test_async_writer_error_surfaces_at_final_drain(tmp_path, monkeypatch):
    """Exit-path contract (PR 1): when the ONLY checkpoint boundary is the
    run's final step there is no later submit to piggyback on — the final
    drain itself must surface the writer error."""
    from heat_tpu.runtime import checkpoint

    def broken(cfg, T, step):
        raise ValueError("sink died on the last boundary")

    monkeypatch.setattr(checkpoint, "save", broken)
    cfg = HeatConfig(n=24, ntime=5, dtype="float32", backend="xla",
                     checkpoint_every=5,
                     checkpoint_dir=str(tmp_path / "ck"))
    with pytest.raises(ValueError, match="last boundary"):
        solve(cfg)


def test_async_io_knob_validation_and_cli():
    with pytest.raises(ValueError, match="async_io"):
        HeatConfig(async_io="maybe")
    from heat_tpu.cli import build_parser

    args = build_parser().parse_args(["run", "--async-io", "off"])
    assert args.async_io == "off"
    assert HeatConfig().use_async_io() is True          # auto resolves on
    assert HeatConfig(async_io="off").use_async_io() is False
