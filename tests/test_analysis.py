"""Invariant guard (ISSUE 11): the static-analysis suite + the dynamic
lock-order watchdog.

Per rule family: one seeded-violation fixture proving the rule FIRES with
the right message, and one clean fixture proving it stays quiet — plus
the dogfood acceptance test (``heat-tpu check`` exits 0 on this repo),
the schema-drift gate, allow-marker semantics, the ``check`` CLI, and the
``HEAT_TPU_LOCKCHECK=1`` watchdog (order violation raises; a real engine
wave under the armed watchdog records zero inversions).

ISSUE 14 adds the races family (Eraser-style lockset inference + the
committed guard map) and the ``HEAT_TPU_RACECHECK`` dynamic sanitizer:
seeded unguarded cross-thread writes must fail statically AND raise
dynamically; guarded/allow-marked patterns stay quiet; guard-map drift
is reviewable, never silent.
"""

import json
import textwrap
from pathlib import Path

import pytest

from heat_tpu.analysis import RULE_FAMILIES, run_checks
from heat_tpu.cli import main
from heat_tpu.runtime import debug

PKG = Path(__file__).resolve().parent.parent / "heat_tpu"


def _tree(tmp_path, files):
    """Write a fixture package tree; returns its root."""
    root = tmp_path / "pkg"
    for rel, body in files.items():
        p = root / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(body))
    return root


def _run(root, rules=None, **kw):
    vs, stats = run_checks(root, rules=rules, **kw)
    return vs, stats


def _msgs(vs, rule):
    return [v.message for v in vs if v.rule == rule]


# --- rule family 1: hot-path purity ----------------------------------------

_PURE_HOT = """
    import numpy as np

    class _GroupRunner:
        def dispatch_fill(self):
            k = self.chunk
            handle = self.eng.dispatch_chunk(k)
            np.maximum(self.dev_rem - k, 0, out=self.dev_rem)
            self.inflight.append(handle)
"""


def test_purity_seeded_violations_fire(tmp_path):
    root = _tree(tmp_path, {"serve/scheduler.py": """
        import numpy as np
        import jax.numpy as jnp

        class _GroupRunner:
            def dispatch_fill(self):
                handle = self.eng.dispatch_chunk(4)
                rem = np.asarray(handle)          # eager D2H
                done = handle.item()              # sync
                handle.block_until_ready()        # sync
                pad = jnp.maximum(rem, 0)         # eager jnp dispatch
                v = float(handle[0])              # scalarization
    """})
    vs, _ = _run(root, rules=["hot-path-purity"])
    msgs = _msgs(vs, "hot-path-purity")
    assert len(msgs) == 5, msgs
    assert any("eager host round trip `np.asarray" in m for m in msgs)
    assert any("device sync `handle.item()`" in m for m in msgs)
    assert any("block_until_ready" in m for m in msgs)
    assert any("eager `jnp.maximum` dispatch" in m for m in msgs)
    assert any("scalarization of a device boundary handle" in m
               for m in msgs)


def test_purity_clean_fixture_passes(tmp_path):
    root = _tree(tmp_path, {"serve/scheduler.py": _PURE_HOT})
    vs, _ = _run(root, rules=["hot-path-purity"])
    assert vs == []


def test_purity_cold_functions_unrestricted(tmp_path):
    # np.asarray outside the hot set (admission, writer thread) is fine
    root = _tree(tmp_path, {"serve/scheduler.py": """
        import numpy as np

        class Engine:
            def _writeback_job(self, rec):
                return np.asarray(rec["T"])
    """})
    vs, _ = _run(root, rules=["hot-path-purity"])
    assert vs == []


def test_purity_allow_marker_sanctions_seam(tmp_path):
    root = _tree(tmp_path, {"serve/engine.py": """
        import numpy as np

        def host_fetch(x):
            # heat-tpu: allow[hot-path-purity] the one sanctioned seam
            return np.asarray(x)
    """})
    vs, _ = _run(root, rules=["hot-path-purity"])
    assert vs == []


def test_bare_allow_marker_is_itself_a_violation(tmp_path):
    root = _tree(tmp_path, {"serve/engine.py": """
        import numpy as np

        def host_fetch(x):
            # heat-tpu: allow[hot-path-purity]
            return np.asarray(x)
    """})
    vs, _ = _run(root, rules=["hot-path-purity"])
    rules = {v.rule for v in vs}
    assert "allow-marker" in rules          # reasonless marker flagged
    assert "hot-path-purity" in rules       # and it does NOT suppress


# --- rule family 2: lock discipline ----------------------------------------

def test_lock_seeded_violations_fire(tmp_path):
    root = _tree(tmp_path, {"serve/scheduler.py": """
        class Engine:
            def _emit(self, rec):
                with self._lock:
                    json_record("serve_request", id=rec["id"])
                    self.prof.note_terminal(rec, 0.0)
                    T = host_fetch(rec["T"])
    """})
    vs, _ = _run(root, rules=["lock-discipline"])
    msgs = _msgs(vs, "lock-discipline")
    assert len(msgs) == 3, msgs
    assert any("I/O call `json_record`" in m for m in msgs)
    assert any("observatory entry `self.prof.note_terminal`" in m
               for m in msgs)
    assert any("device call `host_fetch`" in m for m in msgs)


def test_lock_reverse_order_fires(tmp_path):
    root = _tree(tmp_path, {"runtime/prof.py": """
        class UsageLedger:
            def add(self, engine):
                with self._lock:
                    engine.submit(None)   # observatory -> engine: NO
    """})
    vs, _ = _run(root, rules=["lock-discipline"])
    assert any("reverse of the documented order" in m
               for m in _msgs(vs, "lock-discipline"))


def test_lock_clean_and_correct_order_passes(tmp_path):
    root = _tree(tmp_path, {"serve/scheduler.py": """
        class Engine:
            def submit(self, cfg):
                with self._lock:
                    self._records.append(cfg)
                self.prof.observe_chunk("b", 1, 1, 1, 0.0)  # outside: ok
    """, "runtime/prof.py": """
        class CostModel:
            def observe(self, v):
                with self._lock:
                    self._entries[v] = v
    """})
    vs, _ = _run(root, rules=["lock-discipline"])
    assert vs == []


def test_repo_lock_sites_classified():
    """The rank table must actually match this repo's lock sites (a
    renamed lock silently dropping out of the discipline would make the
    rule vacuous)."""
    from heat_tpu.analysis.core import Context
    from heat_tpu.analysis.locks import _lock_rank
    import ast

    ctx = Context(PKG)
    sched = ctx.source("serve/scheduler.py")
    ranked = set()
    for node in ast.walk(sched.tree):
        if isinstance(node, ast.With):
            for item in node.items:
                r = _lock_rank(sched.rel, item.context_expr)
                if r:
                    ranked.add(r)
    assert "engine" in ranked


# --- rule family 3: traced determinism -------------------------------------

def test_determinism_seeded_violations_fire(tmp_path):
    root = _tree(tmp_path, {"ops/stepper.py": """
        import functools
        import random
        import time

        import jax

        @functools.partial(jax.jit, static_argnums=(1,))
        def advance(x, k):
            t = time.perf_counter()
            r = random.random()
            for item in {3, 1, 2}:
                x = x + item
            return helper(x)

        def helper(x):
            import os
            return x + len(os.environ)
    """})
    vs, _ = _run(root, rules=["traced-determinism"])
    msgs = _msgs(vs, "traced-determinism")
    assert any("wall-clock read `time.perf_counter`" in m for m in msgs)
    assert any("entropy source `random.random`" in m for m in msgs)
    assert any("unordered set" in m for m in msgs)
    # reachability: helper() is flagged through the call graph
    assert any("environment read" in m and "in helper" in m for m in msgs)


def test_determinism_clean_traced_code_passes(tmp_path):
    root = _tree(tmp_path, {"ops/stepper.py": """
        import functools

        import jax
        import jax.numpy as jnp

        @functools.partial(jax.jit, static_argnums=(1,))
        def advance(x, k):
            for item in sorted({3, 1, 2}):   # sorted: deterministic
                x = x + item
            return jnp.maximum(x, 0)

        def untraced_host_helper():
            import time
            return time.perf_counter()       # not reachable from advance
    """})
    vs, _ = _run(root, rules=["traced-determinism"])
    assert vs == []


def test_determinism_covers_repo_entry_points():
    """The rule must actually see this repo's traced surface — dozens of
    jit/pallas_call/shard_map entries (a regression to zero entries would
    pass every fixture while checking nothing)."""
    from heat_tpu.analysis.core import Context
    from heat_tpu.analysis.determinism import _entry_functions

    ctx = Context(PKG)
    entries = sum(len(_entry_functions(s)) for s in ctx.sources)
    assert entries >= 20, entries


# --- rule family 4: mosaic kernel safety -----------------------------------

def _kernel_fixture(body):
    src = ("import jax.numpy as jnp\n"
           "from jax.experimental import pallas as pl\n\n\n"
           "def _make_kernel(r):\n"
           "    def kernel(x_ref, o_ref):\n"
           + textwrap.indent(textwrap.dedent(body).strip("\n"), "        ")
           + "\n    return kernel\n\n\n"
           "out = pl.pallas_call(_make_kernel(0.1), out_shape=None)\n")
    return {"ops/pallas_stencil.py": src}


@pytest.mark.parametrize("body,fragment", [
    ("o_ref[:] = jnp.isfinite(x_ref[:])", "isfinite:"),
    ("""band = x_ref[:].astype(jnp.float32)
mask = (band > 0).astype(jnp.float32)
o_ref[:] = mask * band""", "multiply-mask:"),
    ("""band = x_ref[:].astype(jnp.float32)
maskr = jnp.where(band > 0, 0.0, 0.1)
o_ref[:] = band + maskr * band""", "multiply-mask:"),
    ("""band = x_ref[:].astype(jnp.float32)
store_dt = o_ref.dtype
o_ref[:] = jnp.where(band > 0, band.astype(store_dt), band)""",
     "narrow-select:"),
    ("""band = x_ref[:].astype(jnp.float32)
cur = band[1:-1, :]
o_ref[:] = jnp.roll(cur, 1, 0)""", "shrinking-roll:"),
])
def test_mosaic_seeded_violations_fire(tmp_path, body, fragment):
    root = _tree(tmp_path, _kernel_fixture(body))
    vs, _ = _run(root, rules=["mosaic-kernel-safety"])
    msgs = _msgs(vs, "mosaic-kernel-safety")
    assert any(m.startswith(fragment) for m in msgs), (fragment, msgs)


def test_mosaic_clean_lane_style_kernel_passes(tmp_path):
    # the PR-9 hardened shape: |x|<inf health, select-kept update,
    # storage-round-then-upcast, full-band rotates
    root = _tree(tmp_path, _kernel_fixture("""
        store_dt = o_ref.dtype
        acc_dt = jnp.float32
        band = x_ref[:].astype(acc_dt)
        rolled = jnp.roll(band, 1, 0)
        ok = (jnp.abs(band) < jnp.float32(float("inf"))).all()
        upd = (band + 0.1 * rolled).astype(store_dt).astype(acc_dt)
        keep = band > 0
        o_ref[:] = jnp.where(keep, upd, band).astype(store_dt)
    """))
    vs, _ = _run(root, rules=["mosaic-kernel-safety"])
    assert vs == []


def test_mosaic_ignores_non_kernel_code(tmp_path):
    # host-side planner code in the same file may use isfinite freely
    root = _tree(tmp_path, {"ops/pallas_stencil.py": """
        import numpy as np

        def plan(shape):
            return np.isfinite(np.asarray(shape)).all()
    """})
    vs, _ = _run(root, rules=["mosaic-kernel-safety"])
    assert vs == []


# --- rule family 5: record-schema registry ---------------------------------

_EMITTER = """
    from .logging import json_record

    def tick(n):
        json_record("heartbeat", step=n, ok=True)
"""


def test_schema_registry_roundtrip_and_drift(tmp_path):
    files = {"runtime/beat.py": _EMITTER}
    root = _tree(tmp_path, files)
    reg = root / "analysis" / "schemas" / "records.json"
    # 1) no registry yet: the gate demands one
    vs, _ = _run(root, rules=["record-schema"])
    assert any("missing/unreadable" in v.message for v in vs)
    # 2) --update-schemas writes it; a rerun is clean
    vs, _ = _run(root, rules=["record-schema"], update_schemas=True)
    assert vs == []
    payload = json.loads(reg.read_text())
    assert payload["events"]["heartbeat"]["keys"] == ["ok", "step"]
    vs, _ = _run(root, rules=["record-schema"])
    assert vs == []
    # 3) key drift: add a field without updating the registry
    (root / "runtime/beat.py").write_text(textwrap.dedent("""
        from .logging import json_record

        def tick(n):
            json_record("heartbeat", step=n, ok=True, lag_s=0.0)
    """))
    vs, _ = _run(root, rules=["record-schema"])
    assert any("key-set drift for event 'heartbeat'" in v.message
               and "added ['lag_s']" in v.message for v in vs)
    # 4) new event entirely
    (root / "runtime/beat.py").write_text(textwrap.dedent("""
        from .logging import json_record

        def tick(n):
            json_record("heartbeat", step=n, ok=True)
            json_record("surprise", boom=1)
    """))
    vs, _ = _run(root, rules=["record-schema"])
    assert any("new record event 'surprise'" in v.message for v in vs)


def test_schema_star_kwargs_must_be_resolvable(tmp_path):
    root = _tree(tmp_path, {"runtime/beat.py": """
        from .logging import json_record

        def tick(payload):
            json_record("mystery", **payload)
    """})
    vs, _ = _run(root, rules=["record-schema"], update_schemas=True)
    assert any("unresolvable **payload" in v.message for v in vs)
    # a local dict literal IS resolvable
    root2 = _tree(tmp_path / "b", {"runtime/beat.py": """
        from .logging import json_record

        def tick(n):
            rec = {"step": n, "ok": True}
            rec["extra"] = 1
            json_record("heartbeat", **rec)
    """})
    vs, _ = _run(root2, rules=["record-schema"], update_schemas=True)
    assert vs == []
    reg = json.loads((root2 / "analysis/schemas/records.json").read_text())
    assert reg["events"]["heartbeat"]["keys"] == ["extra", "ok", "step"]


def test_schema_dynamic_event_name_rejected(tmp_path):
    root = _tree(tmp_path, {"runtime/beat.py": """
        from .logging import json_record

        def tick(name):
            json_record(name, a=1)
    """})
    vs, _ = _run(root, rules=["record-schema"], update_schemas=True)
    assert any("non-literal event name" in v.message for v in vs)


def test_committed_registry_matches_scheduler_record_shape():
    """The committed registry's serve_request keys must include the
    load-bearing consumer-facing fields (usage CLI, labs, gateway
    stream all parse these)."""
    reg = json.loads(
        (PKG / "analysis" / "schemas" / "records.json").read_text())
    keys = set(reg["events"]["serve_request"]["keys"])
    assert {"id", "status", "error", "usage", "tenant", "class",
            "placement", "trace_id", "deadline_ms"} <= keys
    assert not any(k.startswith("_") for k in keys)
    assert "T" not in keys   # the field payload is never emitted
    assert {"slo_alert", "mem_watermark", "lane_kernel_fallback",
            "flightrec"} <= set(reg["events"])


# --- the dogfood acceptance gate + CLI --------------------------------------

def test_full_repo_check_is_clean():
    """ISSUE 11 acceptance: `heat-tpu check` exits 0 on the repo."""
    vs, stats = run_checks(PKG)
    assert vs == [], [v.format() for v in vs]
    assert set(stats["per_rule"]) == set(RULE_FAMILIES)
    assert stats["allow_markers"] >= 5   # the sanctioned seams are marked


def test_check_cli_end_to_end(capsys):
    assert main(["check"]) == 0
    out = capsys.readouterr().out
    assert "heat-tpu check: OK" in out
    assert main(["check", "--list-rules"]) == 0
    out = capsys.readouterr().out
    for rid in RULE_FAMILIES:
        assert rid in out
    assert main(["check", "--rules", "nope"]) == 2
    assert main(["check", "--json"]) == 0
    payload = json.loads(capsys.readouterr().out)
    assert payload["violations"] == []
    assert payload["stats"]["files"] > 40


def test_check_cli_fails_on_seeded_tree(tmp_path, capsys):
    root = _tree(tmp_path, {"serve/scheduler.py": """
        import numpy as np

        class _GroupRunner:
            def dispatch_fill(self):
                return np.asarray(self.handle)
    """})
    assert main(["check", "--root", str(root)]) == 1
    out = capsys.readouterr().out
    assert "[hot-path-purity]" in out
    assert "heat-tpu check: FAILED" in out
    assert main(["check", "--root", str(tmp_path / "nowhere")]) == 2


# --- the dynamic lock-order watchdog ----------------------------------------

@pytest.fixture
def lockcheck(monkeypatch):
    monkeypatch.setenv("HEAT_TPU_LOCKCHECK", "1")
    debug.reset_lock_order_stats()
    yield
    debug.reset_lock_order_stats()


def test_make_lock_plain_when_disabled(monkeypatch):
    monkeypatch.delenv("HEAT_TPU_LOCKCHECK", raising=False)
    import threading
    lk = debug.make_lock("engine")
    assert isinstance(lk, type(threading.Lock()))


def test_make_lock_rejects_unknown_rank():
    with pytest.raises(ValueError, match="unknown lock rank"):
        debug.make_lock("mystery:thing")


def test_lock_order_violation_raises_and_records(lockcheck):
    eng = debug.make_lock("engine")
    obs = debug.make_lock("observatory:ledger")
    with eng:
        with obs:
            pass                       # documented direction: fine
    with pytest.raises(debug.LockOrderError, match="inversion"):
        with obs:
            with eng:                  # the deadlock seed
                pass
    stats = debug.lock_order_stats()
    assert ("engine", "observatory:ledger") in [tuple(e)
                                                for e in stats["edges"]]
    assert len(stats["violations"]) == 1
    assert "observatory:ledger" in stats["violations"][0]


def test_lock_same_rank_nesting_raises(lockcheck):
    a = debug.make_lock("observatory:a")
    b = debug.make_lock("observatory:b")
    with pytest.raises(debug.LockOrderError):
        with a:
            with b:
                pass


def test_lock_reentrant_acquire_raises_instead_of_deadlocking(lockcheck):
    eng = debug.make_lock("engine")
    with pytest.raises(debug.LockOrderError, match="reentrant"):
        with eng:
            with eng:
                pass


def test_ordered_lock_backs_a_condition(lockcheck):
    import threading
    lk = debug.make_lock("engine")
    cond = threading.Condition(lk)
    hits = []

    def waiter():
        with cond:
            hits.append("in")
            cond.wait(timeout=2.0)
            hits.append("out")

    t = threading.Thread(target=waiter)
    t.start()
    while "in" not in hits:
        pass
    with cond:
        cond.notify_all()
    t.join(timeout=5)
    assert hits == ["in", "out"]
    assert debug.held_locks() == []
    assert debug.lock_order_stats()["violations"] == []


def test_engine_wave_under_lockcheck_zero_inversions(lockcheck):
    """A real serve wave with the watchdog armed: every lock the engine,
    observatory, tracer, and writer threads take must respect the
    documented order — zero inversions, and the engine->observatory
    edges actually observed (the watchdog saw real traffic)."""
    from heat_tpu.config import HeatConfig
    from heat_tpu.serve import Engine, ServeConfig

    eng = Engine(ServeConfig(lanes=2, chunk=4, buckets=(32,),
                             emit_records=False, keep_fields=True))
    for i in range(4):
        eng.submit(HeatConfig(n=16, ntime=12, dtype="float64"))
    recs = eng.results()
    assert [r["status"] for r in recs] == ["ok"] * 4
    stats = debug.lock_order_stats()
    assert stats["violations"] == []
    assert any(e[0] == "engine" and e[1].startswith("observatory")
               for e in stats["edges"])


def test_info_reports_static_analysis_line(capsys):
    assert main(["info"]) == 0
    out = capsys.readouterr().out
    assert "static analysis: 6 rule families" in out
    assert "lock-order watchdog" in out
    assert "schema registry 29 event(s)" in out
    assert "race guard: guard map" in out
    assert "race sanitizer available" in out


# --- stale allow markers (ISSUE 13 satellite) -------------------------------

def test_consumed_marker_is_not_stale(tmp_path):
    root = _tree(tmp_path, {"serve/engine.py": """
        import numpy as np

        def host_fetch(x):
            # heat-tpu: allow[hot-path-purity] the one sanctioned seam
            return np.asarray(x)
    """})
    vs, stats = _run(root, rules=["hot-path-purity"], strict_allows=True)
    assert vs == []
    assert stats["stale_allows"] == []


def test_stale_marker_rule_no_longer_fires(tmp_path):
    root = _tree(tmp_path, {"serve/engine.py": """
        def pure_math(x):
            # heat-tpu: allow[hot-path-purity] fixed long ago
            return x + 1
    """})
    vs, stats = _run(root, rules=["hot-path-purity"])
    assert vs == []                          # default mode: warn only
    (s,) = stats["stale_allows"]
    assert s["rule"] == "hot-path-purity"
    assert "no longer fires" in s["why"]
    vs, _ = _run(root, rules=["hot-path-purity"], strict_allows=True)
    (v,) = vs
    assert v.rule == "stale-allow" and "no longer fires" in v.message


def test_stale_marker_unknown_rule_id(tmp_path):
    root = _tree(tmp_path, {"serve/engine.py": """
        def f(x):
            # heat-tpu: allow[no-such-rule] typo'd family id
            return x
    """})
    _, stats = _run(root, rules=["hot-path-purity"])
    (s,) = stats["stale_allows"]
    assert "unknown rule id" in s["why"]


def test_unselected_rule_markers_not_judged(tmp_path):
    root = _tree(tmp_path, {"serve/engine.py": """
        def pure_math(x):
            # heat-tpu: allow[hot-path-purity] can't tell without the rule
            return x + 1
    """})
    _, stats = _run(root, rules=["record-schema"], strict_allows=True)
    assert stats["stale_allows"] == []


def test_marker_grammar_in_string_literal_is_inert(tmp_path):
    root = _tree(tmp_path, {"docs_mod.py": """
        HINT = "write `# heat-tpu: allow[hot-path-purity] reason` markers"
    """})
    _, stats = _run(root, strict_allows=True)
    assert stats["allow_markers"] == 0
    assert stats["stale_allows"] == []


def test_repo_has_no_stale_allows():
    vs, stats = run_checks(PKG, strict_allows=True)
    assert stats["stale_allows"] == []
    assert [v for v in vs if v.rule == "stale-allow"] == []


def test_check_cli_strict_allows(tmp_path, capsys):
    root = _tree(tmp_path, {"serve/engine.py": """
        def pure_math(x):
            # heat-tpu: allow[hot-path-purity] stale
            return x + 1
    """})
    assert main(["check", "--root", str(root),
                 "--rules", "hot-path-purity"]) == 0
    assert "warning:" in capsys.readouterr().out
    assert main(["check", "--root", str(root),
                 "--rules", "hot-path-purity", "--strict-allows"]) == 1
    assert "[stale-allow]" in capsys.readouterr().out


# --- dead-code report (ISSUE 13 satellite) ----------------------------------

_DEAD_TREE = {"mod.py": """
    def used():
        return 1

    def dead_helper():
        return 2

    def _private_dead():
        return 3

    VALUE = used()
""", "hooks.py": """
    import http.server

    class Handler(http.server.BaseHTTPRequestHandler):
        def do_GET(self):
            self.chain()

        def chain(self):
            pass
"""}


def test_dead_code_report_finds_only_the_dead(tmp_path):
    from heat_tpu.analysis.deadcode import dead_code_report
    root = _tree(tmp_path, _DEAD_TREE)
    rows = dead_code_report(root, extra_sources=[])
    assert [r["qualname"] for r in rows] == ["dead_helper"]
    # _private_dead: underscore = intentionally internal, not reported;
    # do_GET: framework hook on a based class, exempt — and its callee
    # `chain` is live THROUGH it (hooks propagate reachability)


def test_dead_code_external_entry_points_keep_functions_live(tmp_path):
    from heat_tpu.analysis.deadcode import dead_code_report
    root = _tree(tmp_path, _DEAD_TREE)
    driver = tmp_path / "driver.py"
    driver.write_text("from pkg.mod import dead_helper\ndead_helper()\n")
    assert dead_code_report(root, extra_sources=[driver]) == []


def test_dead_code_cli_informational(tmp_path, capsys):
    root = _tree(tmp_path, _DEAD_TREE)
    assert main(["check", "--root", str(root), "--dead-code"]) == 0
    out = capsys.readouterr().out
    assert "dead_helper" in out and "informational" in out


def test_repo_has_no_dead_code():
    from heat_tpu.analysis.deadcode import dead_code_report
    assert dead_code_report(PKG) == []


# --- rule family 6: races (lockset / guard map, ISSUE 14) -------------------

_RACY_PUMP = """
    import threading

    class Pump:
        def __init__(self):
            self._lock = threading.Lock()
            self.count = 0
            self._thread = None

        def start(self):
            self._thread = threading.Thread(target=self._worker,
                                            name="pump-worker")
            self._thread.start()

        def _worker(self):
            self.count += 1

        def bump(self):
            self.count += 1
"""

_GUARDED_PUMP = _RACY_PUMP.replace(
    "            self.count += 1",
    "            with self._lock:\n                self.count += 1")


def test_races_seeded_unguarded_write_fires(tmp_path):
    root = _tree(tmp_path, {"serve/pump.py": _RACY_PUMP})
    vs, _ = _run(root, rules=["races"], update_schemas=True)
    msgs = _msgs(vs, "races")
    assert len(msgs) == 2        # one per bare write site
    assert all("field Pump.count is written from threads "
               "[client+pump-worker] with no common lock" in m
               for m in msgs)
    payload = json.loads(
        (root / "analysis/schemas/guards.json").read_text())
    assert payload["fields"]["Pump.count"] == "UNGUARDED"


def test_races_guarded_writes_are_quiet(tmp_path):
    root = _tree(tmp_path, {"serve/pump.py": _GUARDED_PUMP})
    vs, _ = _run(root, rules=["races"], update_schemas=True)
    assert _msgs(vs, "races") == []
    payload = json.loads(
        (root / "analysis/schemas/guards.json").read_text())
    assert payload["fields"]["Pump.count"] == "lock:_lock"
    # _thread: written by the client only, never elsewhere
    assert payload["fields"]["Pump._thread"].startswith(
        ("thread-confined(client", "single-writer(client"))
    # committed map in place -> a plain rerun is clean
    vs, _ = _run(root, rules=["races"])
    assert _msgs(vs, "races") == []


def test_races_lock_held_through_private_helper(tmp_path):
    root = _tree(tmp_path, {"serve/pump.py": """
        import threading

        class Pump:
            def __init__(self):
                self._lock = threading.Lock()
                self.count = 0

            def start(self):
                t = threading.Thread(target=self._worker,
                                     name="pump-worker")
                t.start()

            def _worker(self):
                with self._lock:
                    self._bump_locked()

            def bump(self):
                with self._lock:
                    self._bump_locked()

            def _bump_locked(self):
                self.count += 1
    """})
    vs, _ = _run(root, rules=["races"], update_schemas=True)
    assert _msgs(vs, "races") == []
    payload = json.loads(
        (root / "analysis/schemas/guards.json").read_text())
    assert payload["fields"]["Pump.count"] == "lock:_lock"


def test_races_allow_marker_sanctions_bare_writes(tmp_path):
    marked = _RACY_PUMP.replace(
        "            self.count += 1",
        "            self.count += 1  "
        "# heat-tpu: allow[races] GIL-atomic advisory counter")
    root = _tree(tmp_path, {"serve/pump.py": marked})
    vs, _ = _run(root, rules=["races"], update_schemas=True)
    assert _msgs(vs, "races") == []
    payload = json.loads(
        (root / "analysis/schemas/guards.json").read_text())
    assert payload["fields"]["Pump.count"] == \
        "allow(GIL-atomic advisory counter)"


def test_races_guard_map_missing_and_drift(tmp_path):
    files = {"serve/pump.py": _GUARDED_PUMP}
    root = _tree(tmp_path, files)
    # 1) monitored classes exist but no committed map: the gate demands one
    vs, _ = _run(root, rules=["races"])
    assert any("guard map" in m and "missing/unreadable" in m
               for m in _msgs(vs, "races"))
    # 2) generate + clean rerun
    vs, _ = _run(root, rules=["races"], update_schemas=True)
    assert _msgs(vs, "races") == []
    # 3) a new shared field appears -> reviewable drift, not silence
    (root / "serve/pump.py").write_text(textwrap.dedent(
        _GUARDED_PUMP.replace(
            "        def _worker(self):",
            "        def tag(self):\n"
            "            self.flag = True\n\n"
            "        def _worker(self):")))
    vs, _ = _run(root, rules=["races"])
    assert any("new shared field 'Pump.flag'" in m
               for m in _msgs(vs, "races"))
    # 4) a guard change is a concurrency-contract change: the worker
    # write goes bare and the client writer disappears — count drops
    # from lock:_lock to thread-confined(pump-worker)
    (root / "serve/pump.py").write_text(textwrap.dedent(
        _GUARDED_PUMP.replace(
            "        def _worker(self):\n"
            "            with self._lock:\n"
            "                self.count += 1",
            "        def _worker(self):\n"
            "            self.count += 1").replace(
            "        def bump(self):\n"
            "            with self._lock:\n"
            "                self.count += 1",
            "        def bump(self):\n"
            "            pass")))
    vs, _ = _run(root, rules=["races"])
    drift = [m for m in _msgs(vs, "races") if "changed" in m]
    assert any("'Pump.count'" in m and "'lock:_lock'" in m
               and "thread-confined(pump-worker)" in m for m in drift)
    # 5) field gone entirely -> stale committed entry reported
    (root / "serve/pump.py").write_text(textwrap.dedent("""
        import threading

        class Pump:
            def __init__(self):
                self._lock = threading.Lock()
    """))
    vs, _ = _run(root, rules=["races"])
    assert any("'Pump.count'" in m and "no longer observed" in m
               for m in _msgs(vs, "races"))


def test_repo_guard_map_committed_and_clean():
    """Dogfood: the repo's own guard map is committed, loadable, and the
    races family passes against it (what `make check` enforces)."""
    from heat_tpu.analysis.races import load_guard_map
    gmap = load_guard_map(PKG / "analysis" / "schemas" / "guards.json")
    assert gmap is not None and gmap["version"] == 1
    assert len(gmap["fields"]) > 100
    assert "UNGUARDED" not in set(gmap["fields"].values())
    vs, _ = _run(PKG, rules=["races"])
    assert _msgs(vs, "races") == []


# --- the dynamic race sanitizer (HEAT_TPU_RACECHECK) ------------------------

@pytest.fixture
def racecheck(monkeypatch):
    monkeypatch.setenv("HEAT_TPU_RACECHECK", "1")
    debug.reset_race_stats()
    yield
    debug.reset_race_stats()


class _Box:
    def __init__(self, exempt=frozenset()):
        self.lock = debug.make_lock("engine:box")
        self.counter = 0
        debug.instrument_races(self, label="Box", exempt=exempt)


def test_race_instrument_noop_when_disabled(monkeypatch):
    monkeypatch.delenv("HEAT_TPU_RACECHECK", raising=False)
    b = _Box()
    assert type(b) is _Box
    assert debug.race_stats()["instrumented"] == 0


def test_race_bare_write_from_second_thread_raises(racecheck):
    import threading
    b = _Box()
    assert debug.race_stats()["instrumented"] == 1
    b.counter = 1                      # constructing thread touches first
    err = []

    def bad():
        try:
            b.counter = 2
        except debug.RaceError as e:
            err.append(str(e))

    t = threading.Thread(target=bad)
    t.start()
    t.join(timeout=10)
    assert err and "Box.counter" in err[0]
    assert "empty lockset intersection" in err[0]
    findings = debug.race_stats()["findings"]
    assert len(findings) == 1
    assert findings[0]["field"] == "counter"


def test_race_guarded_writes_quiet(racecheck):
    import threading
    b = _Box()

    def work():
        for _ in range(50):
            with b.lock:
                b.counter += 1

    ts = [threading.Thread(target=work) for _ in range(3)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=10)
    assert b.counter == 150
    assert debug.race_stats()["findings"] == []


def test_race_condition_and_try_acquire_compat(racecheck):
    """The sanitizer must see locks acquired through a Condition wrapper
    and via non-blocking try-acquire as held — both feed the same
    per-thread stack the watchdog maintains."""
    import threading
    b = _Box()
    cond = threading.Condition(b.lock)

    def via_cond():
        for _ in range(20):
            with cond:
                b.counter += 1

    def via_try():
        for _ in range(20):
            while not b.lock.acquire(blocking=False):
                pass
            try:
                b.counter += 1
            finally:
                b.lock.release()

    ts = [threading.Thread(target=via_cond),
          threading.Thread(target=via_try)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=10)
    assert b.counter == 40
    assert debug.race_stats()["findings"] == []


def test_race_record_mode_logs_instead_of_raising(monkeypatch, capsys):
    import threading
    monkeypatch.setenv("HEAT_TPU_RACECHECK", "record")
    debug.reset_race_stats()
    dumps = []
    debug.set_flight_dump_hook(lambda reason: dumps.append(reason))
    try:
        b = _Box()
        b.counter = 1
        t = threading.Thread(target=lambda: setattr(b, "counter", 2))
        t.start()
        t.join(timeout=10)
        assert len(debug.race_stats()["findings"]) == 1
        out = capsys.readouterr().out
        assert '"event": "race_detected"' in out
        assert '"field": "counter"' in out
        assert dumps and "race" in dumps[0]
    finally:
        debug.set_flight_dump_hook(None)
        debug.reset_race_stats()


@pytest.mark.filterwarnings(
    "ignore::pytest.PytestUnhandledThreadExceptionWarning")
def test_thread_excepthook_emits_crash_record(capsys):
    import threading
    debug.install_thread_excepthook()
    dumps = []
    debug.set_flight_dump_hook(lambda reason: dumps.append(reason))
    try:
        def boom():
            raise ValueError("seeded crash")

        t = threading.Thread(target=boom, name="crash-fixture")
        t.start()
        t.join(timeout=10)
        out = capsys.readouterr().out
        assert '"event": "thread_crash"' in out
        assert '"thread": "crash-fixture"' in out
        assert '"exc_type": "ValueError"' in out
        assert dumps and "crash-fixture" in dumps[0]
    finally:
        debug.set_flight_dump_hook(None)


def test_engine_wave_under_racecheck_zero_findings(racecheck):
    """A real serve wave with the sanitizer armed: the engine, writer,
    and tracer get instrumented and every cross-thread write must hit a
    consistent candidate lockset — zero findings."""
    from heat_tpu.config import HeatConfig
    from heat_tpu.serve import Engine, ServeConfig

    eng = Engine(ServeConfig(lanes=2, chunk=4, buckets=(32,),
                             emit_records=False, keep_fields=True))
    for i in range(4):
        eng.submit(HeatConfig(n=16, ntime=12, dtype="float64"))
    recs = eng.results()
    assert [r["status"] for r in recs] == ["ok"] * 4
    stats = debug.race_stats()
    assert stats["findings"] == [], stats["findings"]
    assert stats["instrumented"] >= 2   # engine + snapshot writer
