"""Compiled-HLO guard for the exchange formulations (round 3).

The indep ghost-write formulation exists to eliminate a full-local-shard
copy from the compiled multi-device advance (parallel/halo.py). This
pins that property at compile level so a refactor can't silently
reintroduce the copy: on the 4x2 virtual mesh the indep advance must
carry strictly fewer full-shard copy ops than the seq one.
"""

import re

import jax
import jax.numpy as jnp
import pytest

from heat_tpu.backends.sharded import make_padded_carry_machinery
from heat_tpu.config import HeatConfig
from heat_tpu.parallel.mesh import build_mesh


def _full_shape_copies(txt: str, shape: str) -> int:
    return len(re.findall(rf"=\s*{re.escape(shape)}\S*\s+copy\(", txt))


@pytest.mark.parametrize("fuse", [1, 4])
def test_indep_advance_has_fewer_fullshard_copies(fuse):
    n = 64
    mesh = build_mesh(2, (4, 2))
    counts = {}
    for exchange in ("seq", "indep"):
        cfg = HeatConfig(n=n, ntime=8, dtype="float32", backend="sharded",
                         mesh_shape=(4, 2), fuse_steps=fuse,
                         exchange=exchange)
        seed, advance, _ = make_padded_carry_machinery(cfg, mesh)
        Tp = seed(jnp.zeros((n, n), jnp.float32))
        txt = advance.lower(Tp, 8).compile().as_text()
        # the padded local shard: (n/4 + 2w, n/2 + 2w)
        w = fuse
        shape = f"f32[{n // 4 + 2 * w},{n // 2 + 2 * w}]"
        counts[exchange] = _full_shape_copies(txt, shape)
    assert counts["indep"] < counts["seq"], counts
    # absolute pin on the CPU XLA pipeline's copy elision, calibrated
    # against jax 0.9.x / jaxlib 0.9.x — if a jax upgrade trips this while
    # the relative assertion above still holds, the source didn't regress;
    # re-calibrate the pin against the new compiler
    assert counts["indep"] <= 1, counts  # the one loop-structural copy
