"""The numpy oracle vs literal loop transcriptions of the reference, plus
physics properties (the test pyramid of SURVEY.md §4)."""

import numpy as np
import pytest

from heat_tpu.backends import solve
from heat_tpu.backends.serial_np import step_edges_np, step_ghost_np
from heat_tpu.config import HeatConfig
from heat_tpu.grid import initial_condition
from heat_tpu.models import get_model


def literal_serial_loop(T, r, nsteps):
    """Direct transcription of the serial triple loop
    (fortran/serial/heat.f90:61-69): snapshot + interior 5-point update."""
    T = T.copy()
    n = T.shape[0]
    for _ in range(nsteps):
        T_old = T.copy()
        for j in range(1, n - 1):
            for k in range(1, n - 1):
                T[j, k] = T_old[j, k] + r * (
                    T_old[j + 1, k] + T_old[j, k + 1]
                    + T_old[j - 1, k] + T_old[j, k - 1] - 4 * T_old[j, k]
                )
    return T


def literal_ghost_loop(T, r, nsteps, bc):
    """Transcription of the MPI-variant step on one rank: ghost ring at bc,
    ALL owned cells update (fortran/mpi+cuda/heat.F90:206-219, IC :243-251)."""
    n = T.shape[0]
    G = np.full((n + 2, n + 2), bc, dtype=T.dtype)
    G[1:-1, 1:-1] = T
    for _ in range(nsteps):
        Gold = G.copy()
        for j in range(1, n + 1):
            for k in range(1, n + 1):
                G[j, k] = Gold[j, k] + r * (
                    Gold[j + 1, k] + Gold[j, k + 1]
                    + Gold[j - 1, k] + Gold[j, k - 1] - 4 * Gold[j, k]
                )
    return G[1:-1, 1:-1]


def test_step_edges_matches_literal_loops():
    cfg = HeatConfig(n=12, ntime=5, dtype="float64", ic="hat")
    T0 = initial_condition(cfg)
    expect = literal_serial_loop(T0, cfg.r, 5)
    got = T0
    for _ in range(5):
        got = step_edges_np(got, cfg.r)
    np.testing.assert_array_equal(got, expect)


def test_step_ghost_matches_literal_loops():
    cfg = HeatConfig(n=12, ntime=4, dtype="float64", ic="uniform", bc="ghost")
    T0 = initial_condition(cfg)
    expect = literal_ghost_loop(T0, cfg.r, 4, cfg.bc_value)
    got = T0
    for _ in range(4):
        got = step_ghost_np(got, cfg.r, cfg.bc_value)
    np.testing.assert_array_equal(got, expect)


def test_serial_backend_end_to_end():
    cfg = HeatConfig(n=24, ntime=8, dtype="float64", backend="serial")
    res = solve(cfg)
    expect = literal_serial_loop(initial_condition(cfg), cfg.r, 8)
    np.testing.assert_array_equal(res.T, expect)
    assert res.timing.steps == 8


def test_maximum_principle():
    """Diffusion can't create new extrema: min/max bounded by IC/BC."""
    cfg = HeatConfig(n=33, ntime=50, dtype="float64", ic="hat")
    res = solve(cfg.with_(backend="serial"))
    assert res.T.max() <= 2.0 + 1e-12
    assert res.T.min() >= 1.0 - 1e-12


def test_heat_decays_toward_walls():
    """With cold Dirichlet walls the hot spot must lose heat monotonically
    (the intended invariant behind the reference's commented-out sum,
    fortran/mpi+cuda/heat.F90:266-273)."""
    cfg = HeatConfig(n=33, ntime=0, dtype="float64", ic="uniform", bc="ghost",
                     report_sum=True, backend="serial")
    sums = []
    T = initial_condition(cfg)
    for steps in [5, 10, 20, 40]:
        r = solve(cfg.with_(ntime=steps))
        sums.append(r.gsum)
    assert all(sums[i] > sums[i + 1] for i in range(len(sums) - 1))
    assert sums[0] < float(T.sum())


def test_interior_conservation_without_boundary_flux():
    """A uniform field with matching wall temperature is a fixed point."""
    cfg = HeatConfig(n=17, ntime=25, dtype="float64", ic="uniform", bc="ghost",
                     bc_value=2.0, backend="serial")
    res = solve(cfg)
    np.testing.assert_allclose(res.T, 2.0, rtol=0, atol=1e-14)


def test_steady_state_convergence():
    """t->inf: everything relaxes to the wall temperature."""
    cfg = HeatConfig(n=9, ntime=4000, dtype="float64", ic="hat", bc="ghost",
                     backend="serial")
    res = solve(cfg)
    model = get_model(cfg)
    np.testing.assert_allclose(res.T, model.steady_state(cfg), atol=1e-6)


def test_stability_limit():
    model = get_model(HeatConfig(ndim=2))
    assert model.stability_limit() == 0.25
    assert get_model(HeatConfig(ndim=3)).stability_limit() == pytest.approx(1 / 6)


def test_3d_oracle_fixed_point():
    cfg = HeatConfig(n=9, ndim=3, ntime=10, dtype="float64", ic="uniform",
                     bc="ghost", bc_value=2.0, backend="serial")
    res = solve(cfg)
    np.testing.assert_allclose(res.T, 2.0, atol=1e-14)


def test_steady_state_edges_follows_ic_ring():
    """edges-BC t->inf is set by the FROZEN IC boundary ring, not bc_value:
    a uniform-2.0 IC with bc_value=1.0 (the python_cuda variant shape)
    relaxes to 2.0 everywhere."""
    cfg = HeatConfig(n=9, ntime=4000, dtype="float64", ic="uniform",
                     bc="edges", bc_value=1.0, backend="serial")
    T0 = initial_condition(cfg)
    res = solve(cfg)
    model = get_model(cfg)
    np.testing.assert_allclose(res.T, model.steady_state(cfg, T0),
                               atol=1e-6)
    with pytest.raises(ValueError, match="frozen IC boundary"):
        model.steady_state(cfg)
    ramp = np.linspace(0.0, 1.0, 9 * 9).reshape(9, 9)
    with pytest.raises(NotImplementedError, match="harmonic"):
        model.steady_state(cfg, ramp)
