"""Property-based tests (hypothesis): the invariants SURVEY.md SS4 names,
asserted over RANDOM fields, shapes, and decompositions rather than the
handful of fixture configs the example-based tests pin.

Example counts are kept small: every example traces+compiles XLA programs,
and the virtual-device mesh makes each solve a real collective run.
"""

import numpy as np
import pytest

hypothesis = pytest.importorskip("hypothesis")  # not a runtime dependency
from hypothesis import given, settings, strategies as st  # noqa: E402

from heat_tpu.backends import solve
from heat_tpu.backends.serial_np import (step_edges_np, step_ghost_np,
                                         step_periodic_np)
from heat_tpu.config import HeatConfig, parse_input, write_input

COMMON = dict(deadline=None, max_examples=10, derandomize=True,
              print_blob=False)


def _random_field(rng, shape):
    return rng.uniform(1.0, 2.0, shape)


@settings(**COMMON)
@given(st.integers(2, 10), st.integers(0, 6),
       st.sampled_from(["edges", "ghost", "periodic"]),
       st.integers(0, 2**31 - 1))
def test_sharded_matches_serial_on_random_fields(quarter, steps, bc, seed):
    """Golden invariant on arbitrary data: the decomposed run matches the
    serial oracle in f64 for every bc, any grid/step count, on a (2,4)
    mesh (all 8 virtual devices; odd shard widths half the time).

    Tolerance is a few ulps, not zero: fuzzing found that XLA emits FMA
    for some local shapes (e.g. odd 17-wide shards from n=34) where numpy
    does not, a 1-ulp/step codegen difference that no summation-order
    discipline can remove. The fixed-config tests keep their bitwise
    assertions as regression pins (there XLA's codegen happens to match)."""
    n = 4 * quarter  # (2,4) mesh always applies; n/4 odd half the time,
    cfg = HeatConfig(n=n, ntime=steps, dtype="float64", bc=bc,
                     backend="sharded", mesh_shape=(2, 4))
    T0 = _random_field(np.random.default_rng(seed), cfg.shape)
    got = solve(cfg, T0=T0)
    ref = solve(cfg.with_(backend="serial", mesh_shape=None), T0=T0)
    np.testing.assert_allclose(got.T, ref.T, rtol=0,
                               atol=1e-14 * max(steps, 1))


@settings(**COMMON)
@given(st.integers(5, 60), st.integers(5, 300), st.integers(1, 9),
       st.integers(0, 2**31 - 1))
def test_pallas_multistep_matches_sequential_random_shapes(m, n, k, seed):
    """The fused kernel == k sequential steps on arbitrary (non-aligned)
    shapes — the temporal-blocking dependency-cone argument, fuzzed."""
    from heat_tpu.ops.pallas_stencil import ftcs_multistep_edges_pallas
    from heat_tpu.ops.stencil import ftcs_step_edges

    import jax.numpy as jnp

    T = jnp.asarray(_random_field(np.random.default_rng(seed), (m, n)),
                    jnp.float32)
    fused = ftcs_multistep_edges_pallas(T, 0.2, k)
    seq = T
    for _ in range(k):
        seq = ftcs_step_edges(seq, 0.2)
    np.testing.assert_allclose(np.asarray(fused), np.asarray(seq),
                               rtol=0, atol=5e-6)


@settings(**COMMON)
@given(st.integers(4, 64), st.integers(1, 20), st.integers(0, 2**31 - 1))
def test_periodic_conserves_heat_on_random_fields(n, steps, seed):
    """No boundary -> exact conservation, for ANY field (not just the
    shipped ICs)."""
    T = _random_field(np.random.default_rng(seed), (n, n))
    out = T.copy()
    for _ in range(steps):
        out = step_periodic_np(out, 0.25)
    assert np.sum(out, dtype=np.float64) == pytest.approx(
        np.sum(T, dtype=np.float64), rel=1e-12)


@settings(**COMMON)
@given(st.integers(4, 64), st.integers(1, 20), st.integers(0, 2**31 - 1),
       st.sampled_from(["edges", "ghost"]))
def test_maximum_principle_on_random_fields(n, steps, seed, bc):
    """With stable sigma, no interior cell can exceed the initial/boundary
    extremes — for any starting field."""
    T = _random_field(np.random.default_rng(seed), (n, n))
    out = T.copy()
    for _ in range(steps):
        out = (step_edges_np(out, 0.25) if bc == "edges"
               else step_ghost_np(out, 0.25, 1.0))
    if bc == "edges":  # frozen ring: extremes bounded by the IC alone
        lo, hi = T.min(), T.max()
    else:  # ghost ring at bc_value joins the extremes
        lo, hi = min(T.min(), 1.0), max(T.max(), 1.0)
    assert out.min() >= lo - 1e-12 and out.max() <= hi + 1e-12


@settings(**COMMON)
@given(st.integers(3, 10**6), st.floats(1e-3, 1.0), st.floats(1e-3, 1.0),
       st.floats(1e-3, 100.0), st.integers(0, 10**6), st.booleans())
def test_input_dat_roundtrip(n, sigma, nu, dom_len, ntime, soln):
    """write_input -> parse_input is the identity on the physics: repr
    precision must round-trip any config (checkpoint fingerprints depend
    on it)."""
    cfg = HeatConfig(n=n, sigma=sigma, nu=nu, dom_len=dom_len, ntime=ntime,
                     soln=soln)
    import tempfile
    from pathlib import Path

    with tempfile.TemporaryDirectory() as d:
        p = Path(d) / "input.dat"
        write_input(cfg, p)
        back = parse_input(p)
    assert (back.n, back.sigma, back.nu, back.dom_len, back.ntime,
            back.soln) == (n, sigma, nu, dom_len, ntime, soln)
