"""Driver entry points stay importable and runnable."""

import sys
from pathlib import Path

import jax
import numpy as np

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import __graft_entry__ as ge  # noqa: E402


def test_entry_compiles_and_runs():
    fn, args = ge.entry()
    out = jax.jit(fn)(*args)
    out = np.asarray(out)
    assert out.shape == args[0].shape
    assert np.isfinite(out).all()
    # one FTCS step diffuses but preserves bounds
    assert out.max() <= 2.0 + 1e-6 and out.min() >= 1.0 - 1e-6


def test_dryrun_multichip_8():
    ge.dryrun_multichip(8)
