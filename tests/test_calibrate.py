"""heat-tpu calibrate (VERDICT r4 #6): fit-the-planner's-own-model.

The command's value rests on two contracts: (1) the fit inverts the SAME
cost functions the planners rank with (cost_thin_2d / cost_3d are shared,
so there is no formula copy to drift), and (2) a calibration record
round-trips into machine.current() via HEAT_CHIP_CALIBRATION with the
trustworthiness label enforced. Both are pinned here synthetically — no
device measurement involved; the measurement path itself is exercised by
the CLI smoke below and `heat-tpu calibrate --quick` in the bench sweep.
"""

import dataclasses
import json

import pytest

from heat_tpu import machine
from heat_tpu.calibrate import fit_ops_3d, fit_vpu_2d
from heat_tpu.ops import pallas_stencil as ps


@pytest.fixture
def chip():
    return machine.V5E


def test_fit_vpu_2d_recovers_synthetic_rate(chip):
    """Generate t_pp FROM the planner's model at a known vpu rate; the
    fit must recover that rate (to bisection tolerance). This closes the
    loop measurement -> model -> constants: if someone edits
    cost_thin_2d's formula, the fit stays consistent BY CONSTRUCTION."""
    shape, k = (4096, 4096), 16
    plan = ps._plan_2d(shape, "float32", k)
    assert plan[0] == "thin"
    n_pad = ps._round_up(max(shape[1], 128), 128)
    true_vpu = 1.7e12
    t_pp = ps.cost_thin_2d(n_pad, plan[1], "float32",
                           dataclasses.replace(chip, vpu_ops_per_s=true_vpu))
    got = fit_vpu_2d(t_pp, shape, "float32", k, chip)
    assert got == pytest.approx(true_vpu, rel=1e-6)


def test_fit_ops_3d_recovers_synthetic_rate(chip):
    shape, k = (512, 512, 512), 8
    plan = ps._plan_3d(shape, "float32", k)
    assert plan is not None
    (m_pad, mid_pad, _), R, M, kc = plan
    pad = m_pad * mid_pad / (shape[0] * shape[1])
    true_rate = 3.1e12
    t_pp = ps.cost_3d(R, M, kc, "float32",
                      dataclasses.replace(chip, ops_rate_3d=true_rate)) * pad
    got = fit_ops_3d(t_pp, shape, "float32", k, chip)
    assert got == pytest.approx(true_rate, rel=1e-6)


def test_fit_refuses_impossible_measurement(chip):
    """A measurement FASTER than the model's bandwidth floor means the
    model is wrong at that geometry — the fit must refuse, not emit a
    nonsense rate."""
    assert fit_vpu_2d(1e-30, (4096, 4096), "float32", 16, chip) is None
    assert fit_ops_3d(1e-30, (512, 512, 512), "float32", 8, chip) is None


def test_calibration_record_round_trips_through_machine(tmp_path, chip):
    rec = {"trustworthy": True, "platform": "tpu",
           "chip_model": dataclasses.asdict(dataclasses.replace(
               chip, vpu_ops_per_s=1.23e12, calibrated=True))}
    p = tmp_path / "cal.json"
    p.write_text(json.dumps(rec))
    cm = machine.from_calibration(str(p))
    assert cm.vpu_ops_per_s == 1.23e12 and cm.calibrated

    # an untrustworthy record (CPU harness run) must come back labeled
    rec["trustworthy"] = False
    p.write_text(json.dumps(rec))
    assert not machine.from_calibration(str(p)).calibrated


def test_run_refuses_floor_fallback_hbm(tmp_path, monkeypatch, chip):
    """When the HBM stream probe hits the two-point noise floor (the
    tunneled platform's dispatch cost dominating — the failure the first
    on-chip calibrate of round 5 hit), run() must keep the TABLE's HBM
    value in the emitted chip model and must NOT stamp it calibrated:
    writing the ~200x-low raw rate would poison every planner cost model
    pointed at the record (review r5)."""
    from heat_tpu import calibrate as cal

    monkeypatch.setattr(cal, "measure_hbm", lambda **kw: {
        "hbm_bytes_per_s": 4.2e9, "hbm_bytes_per_s_raw": 4.2e9,
        "floor_fallback": True, "buffer_mib": 8, "passes": 2})
    # pretend we're on a TPU so the guard (a TPU-only concern) engages;
    # stub the stencil probes (on_tpu=True would try compiled Pallas on
    # the CPU backend) — the guard/record logic is what's under test
    import jax

    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    monkeypatch.setattr(cal, "_solve_rate",
                        lambda cfg, **kw: (1.5e11, False))
    monkeypatch.setattr(cal, "fit_vpu_2d", lambda *a, **kw: 1.7e12)
    monkeypatch.setattr(cal, "fit_ops_3d", lambda *a, **kw: 3.1e12)
    rec = cal.run(str(tmp_path / "cal.json"), quick=True)
    assert rec["stream"]["floor_fallback"] is True
    assert rec["hbm_fitted"] is False
    assert rec["fit_complete"] is False
    assert rec["chip_model"]["calibrated"] is False
    # the poisoned rate must not reach the model; the table value must
    # (classify() on this CPU host's device_kind falls through to
    # machine._DEFAULT, whose table value run() keeps on fallback)
    assert rec["chip_model"]["hbm_bytes_per_s"] != 4.2e9
    assert rec["chip_model"]["hbm_bytes_per_s"] == pytest.approx(
        machine.classify(jax.devices()[0].device_kind).hbm_bytes_per_s)
    assert rec["vs_table"]["hbm_ratio"] is None


def test_run_refuses_overhead_dominated_stencil_fit(tmp_path, monkeypatch):
    """The stencil-probe twin of the HBM floor guard: a rate whose
    two-point correction fell back to the raw dispatch-laden value must
    not be inverted into vpu/ops3d constants (review r5)."""
    from heat_tpu import calibrate as cal

    monkeypatch.setattr(cal, "measure_hbm", lambda **kw: {
        "hbm_bytes_per_s": 8.1e11, "hbm_bytes_per_s_raw": 7.9e11,
        "floor_fallback": False, "buffer_mib": 8, "passes": 2})
    import jax

    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    monkeypatch.setattr(cal, "_solve_rate",
                        lambda cfg, **kw: (2.8e10, True))  # fell back
    rec = cal.run(str(tmp_path / "cal.json"), quick=True)
    assert rec["sweep_2d"]["overhead_dominated"] is True
    assert rec["sweep_2d"]["vpu_ops_per_s_fit"] is None
    assert rec["sweep_3d"]["ops_rate_3d_fit"] is None
    assert rec["fit_complete"] is False
    assert rec["chip_model"]["calibrated"] is False
    # the un-fitted table rates must remain in the emitted model
    assert rec["chip_model"]["vpu_ops_per_s"] == machine._DEFAULT.vpu_ops_per_s


def test_calibration_env_feeds_current(tmp_path, chip, monkeypatch):
    rec = {"trustworthy": True, "platform": "tpu",
           "chip_model": dataclasses.asdict(dataclasses.replace(
               chip, hbm_bytes_per_s=9.9e11, calibrated=True))}
    p = tmp_path / "cal.json"
    p.write_text(json.dumps(rec))
    monkeypatch.setenv("HEAT_CHIP_CALIBRATION", str(p))
    machine._cache = None  # current() caches per process; reset for test
    try:
        assert machine.current().hbm_bytes_per_s == 9.9e11
    finally:
        machine._cache = None

    # a typo'd path must fail LOUDLY, not plan on the wrong chip
    monkeypatch.setenv("HEAT_CHIP_CALIBRATION", str(p) + ".nope")
    with pytest.raises(OSError):
        machine.current()
    machine._cache = None
