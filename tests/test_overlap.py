"""exchange="overlap": interior compute overlapped with the halo
collectives (VERDICT r3 #5, SURVEY.md §7's "hard part").

The restructuring must be invisible in the numbers: owned values
bit-identical to exchange="indep" (and so to the serial oracle) in f32,
where every per-cell operation sequence is unchanged. bf16 3D may chunk
the interior and band kernels differently (per-shape plans -> different
intermediate bf16 roundings), so it gets a tolerance, not bitwise.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from heat_tpu.backends.sharded import solve
from heat_tpu.config import HeatConfig


def _pair(cfg):
    ref = solve(cfg.with_(exchange="indep"))
    got = solve(cfg.with_(exchange="overlap"))
    return np.asarray(ref.T), np.asarray(got.T)


BASE = dict(dtype="float32", backend="sharded", local_kernel="pallas")


@pytest.mark.parametrize("bc", ["edges", "ghost", "periodic"])
@pytest.mark.parametrize("mesh_shape", [(1, 1), (2, 2), (4, 2)])
def test_overlap_bitwise_2d(bc, mesh_shape):
    cfg = HeatConfig(n=64, ntime=12, bc=bc, mesh_shape=mesh_shape,
                     fuse_steps=4, **BASE)
    ref, got = _pair(cfg)
    np.testing.assert_array_equal(ref, got)


@pytest.mark.parametrize("bc", ["edges", "periodic"])
def test_overlap_bitwise_3d(bc):
    cfg = HeatConfig(n=32, ndim=3, ntime=6, bc=bc, sigma=1 / 6,
                     mesh_shape=(2, 2, 2), fuse_steps=2, **BASE)
    ref, got = _pair(cfg)
    np.testing.assert_array_equal(ref, got)


def test_overlap_bitwise_remainder_chunk():
    # ntime % fuse != 0: the remainder block runs ksteps < wpad through
    # the same split (margins stay wpad-wide)
    cfg = HeatConfig(n=64, ntime=10, mesh_shape=(2, 2), fuse_steps=4,
                     **BASE)
    ref, got = _pair(cfg)
    np.testing.assert_array_equal(ref, got)


@pytest.mark.parametrize("n,fuse", [
    (16, 8),   # L = 8 = w: rim bands ARE the whole shard, interior empty
    (24, 8),   # w < L=12 < 2w: bands overlap mid-shard, interior empty
    (32, 8),   # L = 16 = 2w exactly: interior empty, bands abut
])
def test_overlap_bitwise_tiny_shards(n, fuse):
    cfg = HeatConfig(n=n, ntime=8, mesh_shape=(2, 2), fuse_steps=fuse,
                     **BASE)
    ref, got = _pair(cfg)
    np.testing.assert_array_equal(ref, got)


def test_overlap_matches_serial_oracle():
    from heat_tpu.backends.serial_np import solve as serial_solve

    cfg = HeatConfig(n=48, ntime=10, mesh_shape=(2, 2), fuse_steps=4,
                     **BASE)
    want = serial_solve(cfg.with_(backend="serial")).T.astype(np.float32)
    got = np.asarray(solve(cfg.with_(exchange="overlap")).T)
    np.testing.assert_allclose(got, want, rtol=2e-6, atol=2e-6)


def test_overlap_bf16_close():
    cfg = HeatConfig(n=64, ntime=8, dtype="bfloat16", backend="sharded",
                     local_kernel="pallas", mesh_shape=(2, 2), fuse_steps=4)
    ref = np.asarray(solve(cfg.with_(exchange="indep")).T, np.float32)
    got = np.asarray(solve(cfg.with_(exchange="overlap")).T, np.float32)
    np.testing.assert_allclose(got, ref, rtol=3e-2, atol=3e-2)


def test_overlap_requires_pallas_kernel():
    cfg = HeatConfig(n=64, ntime=4, exchange="overlap", dtype="float32",
                     backend="sharded", local_kernel="xla")
    with pytest.raises(ValueError, match="overlap"):
        solve(cfg)


def test_overlap_staged_comm():
    # the HIP-preset staged (host round-trip) exchange still composes
    cfg = HeatConfig(n=64, ntime=8, comm="staged", mesh_shape=(2, 2),
                     fuse_steps=4, **BASE)
    ref, got = _pair(cfg)
    np.testing.assert_array_equal(ref, got)
