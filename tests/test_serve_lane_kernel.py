"""Pallas-native lane stepping (ISSUE 9): the serve chunk program's two
interchangeable bodies.

The load-bearing contract is the ORACLE relation: the multi-lane Pallas
kernel family (``ops/pallas_stencil.lane_multistep`` — lane axis as a
grid dimension, per-lane mask + countdown gate + isfinite reduction
fused into the stencil pass) must produce, for every request, the exact
bytes the vmapped masked XLA lane program produces — across dtypes,
dimensionality, BCs, dispatch depths, mid-flight admits, lane-tier
growth, and the whole fault-domain repertoire (quarantine, rollback
heal, watchdog). Pallas runs in interpret mode on CPU, so the matrix is
tier-1. The second contract is fallback HONESTY: an unsupported
(bucket, dtype) under a forced/auto Pallas request degrades to XLA as a
structured ``lane_kernel_fallback`` record + counter — loudly, never an
error, never silently. Third: rollback mode dispatches NO standalone
full-stack copy program per chunk (the snapshot is the undonated input
stack itself)."""

import json

import numpy as np
import pytest

from heat_tpu.backends import solve
from heat_tpu.config import HeatConfig
from heat_tpu.runtime import faults
from heat_tpu.serve import Engine, ServeConfig
from heat_tpu.serve.engine import BucketKey, resolve_lane_kernel


@pytest.fixture(autouse=True)
def _fresh_faults():
    faults.reset()
    yield
    faults.reset()


def quiet(**kw) -> ServeConfig:
    kw.setdefault("emit_records", False)
    kw.setdefault("keep_fields", True)
    return ServeConfig(**kw)


def drain(reqs, **kw):
    """Drain ``reqs`` through one engine; records in submit order."""
    eng = Engine(quiet(**kw))
    ids = [eng.submit(cfg) for cfg in reqs]
    by_id = {r["id"]: r for r in eng.results()}
    return eng, [by_id[i] for i in ids]


def assert_byte_identical(recs_a, recs_b):
    for a, b in zip(recs_a, recs_b):
        assert a["status"] == b["status"], (a, b)
        if a["status"] == "ok":
            assert a["T"].dtype == b["T"].dtype
            assert a["T"].tobytes() == b["T"].tobytes(), a["id"]


# --- the bit-identity matrix -------------------------------------------------
#
# Not a full cross-product (each cell compiles interpret-mode Pallas
# programs); the cells below collectively cover {f32, bf16, f64-fallback}
# x {2D, 3D} x {ghost, edges} x dispatch depths {0, 2}, with 5 requests
# over 2 lanes forcing mid-flight admits in every cell.

MATRIX = [
    # (ndim, dtype, bc mix, depth)
    (2, "float32", ("ghost", "edges"), 2),
    (2, "float32", ("edges", "ghost"), 0),
    (2, "bfloat16", ("ghost", "edges"), 2),
    (2, "float64", ("edges", "ghost"), 2),   # fallback path: still exact
    (3, "float32", ("ghost", "edges"), 2),
    (3, "float32", ("edges", "ghost"), 0),
]


def matrix_requests(ndim, dtype, bcs):
    small = 6 if ndim == 3 else 8
    big = 8 if ndim == 3 else 12
    return [
        HeatConfig(n=big, ntime=13, ndim=ndim, dtype=dtype, bc=bcs[0],
                   ic="hat"),
        HeatConfig(n=small, ntime=21, ndim=ndim, dtype=dtype, bc=bcs[1],
                   ic="uniform", nu=0.1),
        HeatConfig(n=big - 2, ntime=5, ndim=ndim, dtype=dtype, bc=bcs[0],
                   ic="hat_small"),
        HeatConfig(n=big, ntime=0, ndim=ndim, dtype=dtype, bc=bcs[1],
                   ic="hat"),
        HeatConfig(n=small + 1, ntime=30, ndim=ndim, dtype=dtype,
                   bc=bcs[0], ic="hat_half", bc_value=2.5),
    ]


@pytest.mark.parametrize("ndim,dtype,bcs,depth", MATRIX)
def test_pallas_lane_program_byte_identical_to_xla(ndim, dtype, bcs, depth):
    reqs = matrix_requests(ndim, dtype, bcs)
    bucket = (8,) if ndim == 3 else (12,)
    kw = dict(lanes=2, chunk=4, buckets=bucket, dispatch_depth=depth)
    eng_x, recs_x = drain(reqs, lane_kernel="xla", **kw)
    eng_p, recs_p = drain(reqs, lane_kernel="pallas", **kw)
    assert all(r["status"] == "ok" for r in recs_x)
    assert_byte_identical(recs_x, recs_p)
    if dtype == "float64":
        # no f64 lane kernel: forced pallas degrades per (bucket, tier)
        assert eng_p.lane_kernel_fallbacks >= 1
    else:
        assert eng_p.lane_kernel_fallbacks == 0
    # both engines also match the solo oracle (same-dtype CPU contract)
    for r, cfg in zip(recs_x, reqs):
        if r["status"] == "ok":
            assert np.array_equal(r["T"], solve(cfg).T)


def test_pallas_npz_outputs_byte_identical_across_depths(tmp_path):
    """The acceptance spelling: published npz files from
    --serve-lane-kernel pallas and xla are byte-identical at dispatch
    depths 0 and 2."""
    reqs = matrix_requests(2, "float32", ("ghost", "edges"))
    for depth in (0, 2):
        outs = {}
        for kernel in ("xla", "pallas"):
            d = tmp_path / f"{kernel}-{depth}"
            _, recs = drain(reqs, lane_kernel=kernel, lanes=2, chunk=4,
                            buckets=(12,), dispatch_depth=depth,
                            out_dir=str(d))
            outs[kernel] = d
        for p in sorted(outs["xla"].glob("*.npz")):
            q = outs["pallas"] / p.name
            with np.load(p) as a, np.load(q) as b:
                assert a["T"].dtype == b["T"].dtype
                assert a["T"].tobytes() == b["T"].tobytes(), p.name


def test_lane_tier_growth_under_pallas_stays_exact():
    """Online admission outgrows the born tier: the grown Pallas group
    transplants occupants bit-exactly (padded-slab crop + reload)."""
    results = {}
    for kernel in ("xla", "pallas"):
        eng = Engine(quiet(lanes=4, chunk=4, buckets=(12,),
                           lane_kernel=kernel))
        eng.start()
        try:
            first = eng.submit(HeatConfig(n=10, ntime=40, dtype="float32",
                                          bc="ghost"))
            eng.wait(first, timeout=0.05)   # let a tier-1 group form
            rest = [eng.submit(HeatConfig(n=8 + (i % 3), ntime=20 + 4 * i,
                                          dtype="float32", bc="ghost",
                                          ic="hat_small"))
                    for i in range(5)]
            recs = [eng.wait(rid, timeout=60)
                    for rid in [first] + rest]
        finally:
            eng.shutdown(timeout=60)
        assert all(r is not None and r["status"] == "ok" for r in recs)
        results[kernel] = (eng, [eng._by_id[r["id"]].get("T")
                                 for r in recs])
    assert results["pallas"][0].lane_grows >= 1
    for a, b in zip(results["xla"][1], results["pallas"][1]):
        assert a is not None and b is not None
        assert a.tobytes() == b.tobytes()


# --- fault domains on the Pallas kernel --------------------------------------


CHAOS_REQS = [
    HeatConfig(n=10, ntime=12, dtype="float32", bc="ghost"),
    HeatConfig(n=12, ntime=20, dtype="float32", bc="edges",
               ic="hat_small"),
    HeatConfig(n=8, ntime=16, dtype="float32", bc="ghost", ic="uniform"),
]


@pytest.mark.parametrize("depth", [0, 2])
def test_quarantine_isolates_poisoned_lane_on_pallas(depth):
    ids = [f"r{i}" for i in range(len(CHAOS_REQS))]
    kw = dict(lanes=2, chunk=4, buckets=(12,), dispatch_depth=depth,
              lane_kernel="pallas")

    def run(inject):
        eng = Engine(quiet(inject=inject, **kw))
        for rid, cfg in zip(ids, CHAOS_REQS):
            eng.submit(cfg, request_id=rid)
        by_id = {r["id"]: r for r in eng.results()}
        return eng, [by_id[i] for i in ids]

    eng, recs = run("lane-nan@6:req=r1")
    assert [r["status"] for r in recs] == ["ok", "nonfinite", "ok"]
    assert eng.lanes_quarantined == 1
    _, clean = run("")
    for i in (0, 2):   # co-scheduled lanes bit-identical to a clean run
        assert recs[i]["T"].tobytes() == clean[i]["T"].tobytes()


@pytest.mark.parametrize("depth", [0, 2])
def test_rollback_heals_transient_poison_on_pallas(depth):
    kw = dict(lanes=2, chunk=4, buckets=(12,), dispatch_depth=depth,
              lane_kernel="pallas")
    eng = Engine(quiet(on_nan="rollback", inject="lane-nan@6:req=r1",
                       **kw))
    for i, cfg in enumerate(CHAOS_REQS):
        eng.submit(cfg, request_id=f"r{i}")
    by_id = {r["id"]: r for r in eng.results()}
    assert all(by_id[f"r{i}"]["status"] == "ok" for i in range(3))
    assert eng.rollbacks >= 1
    _, clean = drain(CHAOS_REQS, **kw)
    for i in range(3):   # healed bit-identically
        assert by_id[f"r{i}"]["T"].tobytes() == clean[i]["T"].tobytes()


def test_fetch_watchdog_fails_group_cleanly_on_pallas(tmp_path):
    eng = Engine(quiet(lanes=2, chunk=4, buckets=(12,), dispatch_depth=2,
                       lane_kernel="pallas", fetch_timeout_s=0.2,
                       inject="fetch-hang@2:ms=60000",
                       flight_dir=str(tmp_path)))
    for i, cfg in enumerate(CHAOS_REQS):
        eng.submit(cfg, request_id=f"r{i}")
    records = eng.results()   # must return, not hang
    assert eng.watchdog_fired == 1
    assert all(r["status"] in ("ok", "error") for r in records)
    assert any(r["status"] == "error"
               and "fetch-watchdog" in (r["error"] or "")
               for r in records)


# --- rollback copy tax (the second tentpole half) ----------------------------


def test_rollback_mode_dispatches_no_standalone_copy_program(monkeypatch):
    """Acceptance: --serve-on-nan rollback keeps every in-flight boundary
    restorable WITHOUT a per-chunk full-stack copy — the boundary
    snapshot is the undonated input stack itself. device_snapshot (the
    old copy program) must never run on the dispatch path, on either
    kernel; results stay byte-identical to on-nan=fail."""
    from heat_tpu.runtime import async_io

    calls = {"n": 0}
    real = async_io.device_snapshot

    def spy(T):
        calls["n"] += 1
        return real(T)

    monkeypatch.setattr(async_io, "device_snapshot", spy)
    reqs = matrix_requests(2, "float32", ("ghost", "edges"))
    for kernel in ("xla", "pallas"):
        calls["n"] = 0
        eng, recs = drain(reqs, lanes=2, chunk=4, buckets=(12,),
                          dispatch_depth=2, on_nan="rollback",
                          lane_kernel=kernel)
        assert eng.chunks_dispatched > 0
        assert calls["n"] == 0, (kernel, calls)
        _, plain = drain(reqs, lanes=2, chunk=4, buckets=(12,),
                         dispatch_depth=2, lane_kernel=kernel)
        assert_byte_identical(plain, recs)


def test_rollback_snapshot_survives_later_admissions():
    """The aliasing hazard the donate=False contract exists for: a lane
    swap (admission) after a snapshot was taken must not invalidate the
    snapshot another lane's rollback later restores from. 3 requests
    over 1 lane force admissions between boundaries; the poisoned
    request must still heal from a last-good snapshot."""
    eng = Engine(quiet(lanes=1, chunk=4, buckets=(12,),
                       dispatch_depth=2, on_nan="rollback",
                       lane_kernel="pallas",
                       inject="lane-nan@6:req=r1"))
    cfgs = [HeatConfig(n=10, ntime=12, dtype="float32", bc="ghost"),
            HeatConfig(n=10, ntime=12, dtype="float32", bc="ghost",
                       ic="hat_small"),
            HeatConfig(n=10, ntime=12, dtype="float32", bc="ghost",
                       ic="uniform")]
    for i, cfg in enumerate(cfgs):
        eng.submit(cfg, request_id=f"r{i}")
    by_id = {r["id"]: r for r in eng.results()}
    assert all(by_id[f"r{i}"]["status"] == "ok" for i in range(3))
    assert eng.rollbacks >= 1


# --- fallback honesty --------------------------------------------------------


def test_forced_pallas_on_f64_degrades_loudly_not_silently(capsys):
    eng = Engine(ServeConfig(lanes=2, chunk=4, buckets=(12,),
                             lane_kernel="pallas", emit_records=False,
                             keep_fields=True))
    eng.submit(HeatConfig(n=10, ntime=9, dtype="float64", bc="ghost"))
    (rec,) = eng.results()
    assert rec["status"] == "ok"          # never an error
    assert eng.lane_kernel_fallbacks == 1
    assert eng.summary()["lane_kernel_fallbacks"] == 1
    out = capsys.readouterr().out
    # the structured record names the bucket and the reason
    rows = [json.loads(line) for line in out.splitlines()
            if line.startswith("{")
            and json.loads(line).get("event") == "lane_kernel_fallback"]
    assert len(rows) == 1
    assert rows[0]["bucket"] == "2d/n12/float64/ghost"
    assert rows[0]["requested"] == "pallas"
    assert "f64" in rows[0]["reason"] or "float64" in rows[0]["reason"]


def test_fallback_deduped_per_bucket_tier_and_counted_per_tier():
    """Two f64 waves through one engine: the (bucket, tier) fallback is
    recorded once, not once per wave; a second bucket adds a second."""
    eng = Engine(quiet(lanes=2, chunk=4, buckets=(12, 16),
                       lane_kernel="pallas"))
    eng.submit(HeatConfig(n=10, ntime=5, dtype="float64"))
    eng.results()
    assert eng.lane_kernel_fallbacks == 1
    eng.submit(HeatConfig(n=10, ntime=5, dtype="float64", ic="uniform"))
    eng.results()   # warm re-run, same (bucket, tier)
    assert eng.lane_kernel_fallbacks == 1
    eng.submit(HeatConfig(n=14, ntime=5, dtype="float64"))
    eng.results()   # second bucket
    assert eng.lane_kernel_fallbacks == 2


def test_auto_never_errors_and_is_silent_off_tpu():
    """auto off-TPU resolves XLA as policy (no fallback record — nothing
    degraded); every dtype serves."""
    reqs = [HeatConfig(n=10, ntime=8, dtype=d)
            for d in ("float32", "float64", "bfloat16")]
    eng, recs = drain(reqs, lanes=2, chunk=4, buckets=(12,),
                      lane_kernel="auto")
    assert all(r["status"] == "ok" for r in recs)
    assert eng.lane_kernel_fallbacks == 0


def test_resolve_lane_kernel_rules(monkeypatch):
    key32 = BucketKey(2, 12, "float32", "ghost")
    key64 = BucketKey(2, 12, "float64", "ghost")
    assert resolve_lane_kernel("xla", key32) == ("xla", None)
    assert resolve_lane_kernel("pallas", key32) == ("pallas", None)
    k, reason = resolve_lane_kernel("pallas", key64)
    assert k == "xla" and reason is not None
    # auto off-TPU: XLA, no reason (policy, not degradation)
    assert resolve_lane_kernel("auto", key32) == ("xla", None)
    # auto on (faked) TPU: pallas where a plan exists, loud elsewhere
    import jax
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    assert resolve_lane_kernel("auto", key32) == ("pallas", None)
    k, reason = resolve_lane_kernel("auto", key64)
    assert k == "xla" and reason is not None


def test_metrics_surface_lane_kernel_gauge_and_cost_kernel_label():
    from heat_tpu.serve.gateway import render_metrics

    eng, _ = drain([HeatConfig(n=10, ntime=8, dtype="float32",
                               bc="ghost")],
                   lanes=1, chunk=4, buckets=(12,), lane_kernel="pallas")
    text = render_metrics(eng)
    assert "heat_tpu_serve_lane_kernel_fallbacks_total" in text
    assert 'kernel="pallas"' in text   # cost rows carry the kernel key


def test_serve_config_validates_lane_kernel():
    with pytest.raises(ValueError, match="lane_kernel"):
        ServeConfig(lane_kernel="mosaic")
    for v in ("auto", "pallas", "xla"):
        assert ServeConfig(lane_kernel=v).lane_kernel == v


def test_serve_cli_lane_kernel_flag(tmp_path, capsys, monkeypatch):
    from heat_tpu.cli import main

    monkeypatch.chdir(tmp_path)
    req = tmp_path / "reqs.jsonl"
    req.write_text(json.dumps({"n": 10, "ntime": 6, "dtype": "float32",
                               "bc": "ghost"}) + "\n")
    rc = main(["serve", "--requests", str(req), "--lanes", "1",
               "--chunk", "4", "--buckets", "12",
               "--serve-lane-kernel", "pallas"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "lane kernel pallas" in out
    # bad value is an argparse rejection
    with pytest.raises(SystemExit):
        main(["serve", "--requests", str(req),
              "--serve-lane-kernel", "mosaic"])


def test_lane_kernel_lab_harness_smoke(tmp_path):
    """The three-way A/B harness runs end-to-end on a tiny 2-lane
    workload and emits every field the committed artifact and perfcheck
    rely on (speed not asserted — plumbing, not perf)."""
    import importlib.util
    import sys
    from pathlib import Path

    bench_dir = Path(__file__).resolve().parent.parent / "benchmarks"
    sys.path.insert(0, str(bench_dir))
    try:
        spec = importlib.util.spec_from_file_location(
            "lane_lab_smoke", bench_dir / "serve_lane_kernel_lab.py")
        lab = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(lab)
        out = tmp_path / "lane_lab.json"
        rc = lab.main(["--requests", "4", "--lanes", "2", "--chunk", "8",
                       "--out", str(out)])
    finally:
        sys.path.remove(str(bench_dir))
    assert rc == 0
    rec = json.loads(out.read_text())
    assert rec["bench"] == "serve_lane_kernel_lab"
    assert rec["bit_identical"] is True
    assert rec["solo_sample_identical"] is True
    assert rec["zero_fallbacks"] is True
    for side, kern in (("pallas", "pallas"), ("xla", "xla")):
        assert rec[side]["ok"] == 4
        assert rec[side]["lane_kernel_fallbacks"] == 0
        rows = rec[side]["cost_model"]
        assert rows and all(e["kernel"] == kern for e in rows)
    assert rec["pallas_vs_xla"] is not None
    assert rec["pallas_vs_solo"] is not None
    assert rec["solo_pallas"]["points_per_s"] > 0
