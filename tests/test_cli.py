"""CLI driver: the program-heat contract end to end (discovery, dumps,
stdout lines — fortran/serial/heat.f90:11-13,50-55,73-83)."""

import numpy as np
import pytest

from heat_tpu.cli import main
from heat_tpu.io import read_dat


@pytest.fixture
def input_dat(tmp_cwd):
    (tmp_cwd / "input.dat").write_text("32 0.25 0.05 2.0 5 1\n")
    return tmp_cwd


def test_run_writes_soln_and_prints_contract_lines(input_dat, capsys):
    rc = main(["run", "--backend", "xla", "--dtype", "float64", "--write-int"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "simulation completed!!!!" in out      # serial/heat.f90:73
    assert "total time:" in out                   # serial/heat.f90:74
    assert "Average time per timestep:" in out    # hip/heat.F90:323
    assert (input_dat / "int.dat").exists()
    axes, T = read_dat(input_dat / "soln.dat")
    assert T.shape == (32, 32)
    # int.dat holds the IC; soln.dat the diffused field (the hat edge
    # smears immediately; sum is conserved until the front hits the walls)
    _, T0 = read_dat(input_dat / "int.dat")
    assert T0.max() == 2.0
    assert not np.array_equal(T, T0)
    assert np.abs(T - T0).max() > 1e-3


def test_run_missing_input(tmp_cwd, capsys):
    rc = main(["run"])
    assert rc == 2
    assert "not found" in capsys.readouterr().err


def test_run_variant_preset(input_dat, capsys):
    rc = main(["run", "--variant", "cuda_cuf", "--json"])
    assert rc == 0
    out = capsys.readouterr().out
    assert '"backend": "xla"' in out


def test_run_sharded_with_mesh(input_dat):
    rc = main(["run", "--backend", "sharded", "--dtype", "float64",
               "--mesh", "4x2", "--report-sum"])
    assert rc == 0
    assert (input_dat / "soln.dat").exists()
    # per-shard files (reference per-rank contract) alongside the merged one
    shard_files = sorted(input_dat.glob("soln0*.dat"))
    assert len(shard_files) == 8
    merged = read_dat(input_dat / "soln.dat")[1]
    _, blk0 = read_dat(shard_files[0])
    np.testing.assert_array_equal(blk0, merged[:8, :16])


def test_heartbeat_lines_identical_across_backends(input_dat, capsys):
    main(["run", "--backend", "serial", "--dtype", "float64",
          "--heartbeat-every", "2"])
    serial_lines = [l for l in capsys.readouterr().out.splitlines()
                    if "time_it" in l]
    main(["run", "--backend", "xla", "--dtype", "float64",
          "--heartbeat-every", "2"])
    xla_lines = [l for l in capsys.readouterr().out.splitlines()
                 if "time_it" in l]
    assert serial_lines == xla_lines and len(serial_lines) == 2


def test_soln_flag_gating(tmp_cwd):
    # soln=0 in input.dat -> no dump (mpi+cuda/heat.F90:277: gated write)
    (tmp_cwd / "input.dat").write_text("16 0.25 0.05 2.0 2 0\n")
    rc = main(["run", "--backend", "serial", "--dtype", "float64"])
    assert rc == 0
    assert not (tmp_cwd / "soln.dat").exists()


def test_viz(input_dat):
    pytest.importorskip("matplotlib")
    main(["run", "--backend", "serial", "--dtype", "float64", "--write-int"])
    rc = main(["viz", "soln.dat", "--save", "sol.png"])
    assert rc == 0
    assert (input_dat / "sol.png").stat().st_size > 0
    # the reference's init.py workflow: render the pre-solve dump too
    assert main(["viz", "int.dat", "--save", "init.png"]) == 0
    assert (input_dat / "init.png").stat().st_size > 0


def test_info(capsys):
    assert main(["info"]) == 0
    out = capsys.readouterr().out
    assert "devices:" in out
    # PR-2's gloo unbreak and the persistent compile cache must be visible
    # to users, not only discoverable through a failed launch
    assert "gloo CPU collectives:" in out
    assert "compile cache:" in out
    assert ("warm" in out) or ("cold/empty" in out)
    # the trace defaults line (ISSUE 7): flight recorder + export knobs
    assert "trace defaults: flight recorder on" in out
    assert "--trace FILE" in out and "HEAT_TPU_TRACE" in out
    # the numerics observatory + prober lines (ISSUE 15): solution-quality
    # telemetry defaults and the canary knobs must be discoverable here
    assert "numerics observatory: on by default" in out
    assert "steady-tol" in out and "guard warn" in out
    assert "prober: off by default (--probe-interval" in out
    assert "sine-eigenmode" in out


def test_serve_cli_numerics_flags(tmp_cwd, capsys):
    """--numerics gates the observatory (and its summary line);
    --probe-interval validates against missing --listen at parse time."""
    (tmp_cwd / "reqs.jsonl").write_text(
        '{"id": "a", "n": 12, "ntime": 16, "dtype": "float32",'
        ' "ic": "uniform"}\n')
    base = ["serve", "--requests", "reqs.jsonl", "--buckets", "12",
            "--chunk", "4"]
    assert main(base) == 0
    out = capsys.readouterr().out
    # uniform under frozen edges converges instantly: one steady lane
    assert "numerics: 1 steady lane(s), 0 violation(s) (guard warn)" in out
    assert '"event": "steady_state"' in out

    assert main(base + ["--numerics", "off"]) == 0
    out = capsys.readouterr().out
    assert "steady lane(s)" not in out and "steady_state" not in out

    with pytest.raises(SystemExit):   # argparse rejects a bad guard
        main(base + ["--numerics-guard", "page-someone"])
    assert main(base + ["--probe-interval", "5"]) == 2
    assert "--probe-interval needs --listen" in capsys.readouterr().err
    assert main(base + ["--probe-interval", "-1", "--listen",
                        "127.0.0.1:0"]) == 2


def test_bad_mesh_arg():
    with pytest.raises(SystemExit):
        main(["run", "--mesh", "fourbytwo"])


def test_cli_plan_subcommand(tmp_cwd, capsys):
    """`heat-tpu plan` explains the execution plan without touching devices:
    kernel choice + geometry, mesh/halo economics, run-path validation."""
    from heat_tpu.cli import main

    (tmp_cwd / "input.dat").write_text("4096 0.25 0.05 2.0 100 0\n")
    assert main(["plan", "--backend", "pallas", "--dtype", "float32"]) == 0
    out = capsys.readouterr().out
    assert "thin-band 2D" in out and "per-pass chunk 16" in out

    assert main(["plan", "--backend", "sharded", "--dtype", "float32",
                 "--mesh", "4x4"]) == 0
    out = capsys.readouterr().out
    assert "local block 1024x1024" in out and "halo: width 23" in out  # k* = round(sqrt(1024/2))

    # f64 -> XLA fallback is reported honestly
    assert main(["plan", "--variant", "cuda_kernel"]) == 0
    assert "XLA fused stencil" in capsys.readouterr().out

    # run-path validation applies: bad mesh rank / divisibility
    assert main(["plan", "--backend", "sharded", "--ndim", "3",
                 "--mesh", "4x2"]) == 2
    assert main(["plan", "--backend", "sharded", "--mesh", "3x3"]) == 2


def test_cli_bench_subcommand(capsys):
    """`heat-tpu bench` — the headline measurement inline (shared core
    with bench.py), shrunken off-TPU; the JSON record must parse and
    carry the bench.py field contract."""
    import json

    from heat_tpu.cli import main

    assert main(["bench", "--n", "64", "--steps", "8", "--repeats", "1"]) == 0
    out = capsys.readouterr().out
    rec = json.loads([l for l in out.splitlines() if l.startswith("{")][-1])
    assert rec["metric"] == "grid_points_per_sec_per_chip_64x64_f32_pallas"
    assert rec["value"] > 0 and rec["raw_single_call"] > 0
    assert set(rec) >= {"metric", "value", "unit", "vs_baseline",
                        "raw_single_call", "platform"}


def test_cli_crash_resume_flow(tmp_cwd, capsys):
    """The elastic-recovery story (SURVEY SS5: the reference ignores its
    MPI error codes entirely): a run dies mid-job, the operator re-issues
    the SAME command, and the solve resumes from the latest checkpoint
    instead of restarting — no flags beyond --checkpoint-every, no
    in-process retry loop (a dead backend needs a fresh process anyway,
    see TROUBLESHOOTING.md)."""
    (tmp_cwd / "input.dat").write_text("16 0.25 0.05 2.0 6 1\n")
    args = ["run", "--backend", "serial", "--dtype", "float64",
            "--checkpoint-every", "2"]

    # the "crashed" first attempt: same config, killed after step 4
    # (emulated by a shorter ntime writing the same checkpoint stream)
    from heat_tpu.backends import solve as _solve
    from heat_tpu.config import HeatConfig, parse_input

    cfg = parse_input("input.dat").with_(backend="serial", dtype="float64",
                                         checkpoint_every=2)
    _solve(cfg.with_(ntime=4))          # dies "mid-run" at step 4
    capsys.readouterr()

    assert main(args) == 0              # operator re-runs the same command
    out = capsys.readouterr().out
    assert "resumed from" in out        # picked up at step 4, not step 0
    # and the result equals an uninterrupted 6-step run
    import numpy as np
    from heat_tpu.io import read_dat

    _, T = read_dat("soln.dat")
    clean = _solve(HeatConfig(n=16, ntime=6, dtype="float64",
                              backend="serial"))
    np.testing.assert_allclose(T, clean.T, rtol=0, atol=1e-12)


def test_viz_3d_midplane(tmp_cwd):
    """The 3-D extension's quadruplet files render as the mid-plane slice
    (the reference has no 3-D viz to imitate)."""
    pytest.importorskip("matplotlib")
    (tmp_cwd / "input.dat").write_text("12 0.15 0.05 2.0 2 1\n")
    assert main(["run", "--backend", "serial", "--dtype", "float64",
                 "--ndim", "3"]) == 0
    assert main(["viz", "soln.dat", "--ndim", "3", "--save", "s3.png"]) == 0
    assert (tmp_cwd / "s3.png").stat().st_size > 0


def test_variant_serial_default_int_dat_and_heartbeat(input_dat, capsys):
    """Default-behavior parity (VERDICT r2 missing #3): every single-process
    Fortran variant writes int.dat unconditionally before solving
    (fortran/serial/heat.f90:50-55) and prints time_it every step (:62) —
    the presets must do both without extra flags."""
    rc = main(["run", "--variant", "serial"])
    assert rc == 0
    assert (input_dat / "int.dat").exists()
    out = capsys.readouterr().out
    assert out.count("time_it:") == 5  # ntime=5, every step


def test_variant_default_opt_outs(input_dat, capsys):
    rc = main(["run", "--variant", "serial", "--no-write-int",
               "--heartbeat-every", "0"])
    assert rc == 0
    assert not (input_dat / "int.dat").exists()
    assert "time_it" not in capsys.readouterr().out


def test_variant_python_serial_no_int_dat(input_dat, capsys):
    """The python reference variants write no int.dat and print no
    heartbeat (python/serial/heat.py plots inline instead) — their presets
    must not invent either."""
    rc = main(["run", "--variant", "python_serial"])
    assert rc == 0
    assert not (input_dat / "int.dat").exists()
    assert "time_it" not in capsys.readouterr().out


def test_cfl_stability_warning(tmp_cwd, capsys):
    """VERDICT r2 missing #4: sigma above 1/(2*ndim) warns loudly on the
    run path (a warning, not an error — the reference admits unstable
    configs; FTCS bound per fortran/serial/heat.f90:15-17)."""
    (tmp_cwd / "input.dat").write_text("16 0.25 0.05 2.0 2 0\n")
    # sigma=0.25 == the 2D bound: stable, silent
    assert main(["run", "--backend", "serial", "--dtype", "float64"]) == 0
    assert "stability bound" not in capsys.readouterr().out
    # same sigma in 3D exceeds 1/6: loud warning, run still completes
    assert main(["run", "--backend", "serial", "--dtype", "float64",
                 "--ndim", "3"]) == 0
    out = capsys.readouterr().out
    assert "WARNING" in out and "1/(2*ndim)" in out
    # sigma=0.15 < 1/6: silent again
    (tmp_cwd / "input.dat").write_text("16 0.15 0.05 2.0 2 0\n")
    assert main(["run", "--backend", "serial", "--dtype", "float64",
                 "--ndim", "3"]) == 0
    assert "stability bound" not in capsys.readouterr().out
    # plan warns the same way, without touching devices
    (tmp_cwd / "input.dat").write_text("16 0.25 0.05 2.0 2 0\n")
    assert main(["plan", "--backend", "serial", "--ndim", "3"]) == 0
    assert "stability bound" in capsys.readouterr().out


def test_cli_bench_off_tpu_label(capsys):
    """ADVICE r2: the roofline percentage is a v5e constant — off-TPU the
    human line reports the raw rate and says why there is no percentage."""
    import jax

    assert jax.default_backend() != "tpu"  # conftest pins cpu
    assert main(["bench", "--n", "64", "--steps", "8", "--repeats", "1"]) == 0
    out = capsys.readouterr().out
    assert "roofline % only meaningful on TPU" in out
    assert "% of the one-pass v5e HBM roofline" not in out
