"""Chaos end-to-end: the self-healing launch supervisor over a REAL
two-process world (ISSUE 2 acceptance). An injected mid-run worker crash
must restart the world, resume from the agreed shard checkpoint, and finish
bit-identical to an uninterrupted run; a corrupted newest checkpoint must
fall back one step with the bad file quarantined.

Marked ``slow`` (multi-process worlds spawn jax interpreters; ~15 s each)
so tier-1 (`-m 'not slow'`) keeps its timeout — run explicitly with
``pytest -m slow tests/test_chaos.py``.
"""

import numpy as np
import pytest

from heat_tpu.cli import main
from heat_tpu.io import read_dat

pytestmark = pytest.mark.slow

_RUN = ["run", "--backend", "sharded", "--dtype", "float64", "--mesh", "2x1",
        "--checkpoint-every", "2", "--async-io", "off"]
# --async-io off: the crash must land AFTER the boundary's checkpoint is
# durable, deterministically — the async writer would race the injected
# os._exit and make the resume step nondeterministic (fine in production,
# wrong for a bit-identity acceptance test).


@pytest.fixture(autouse=True)
def _fast_backoff(monkeypatch):
    monkeypatch.setenv("HEAT_TPU_RESTART_BACKOFF_S", "0.05")


def _soln_shards(d):
    files = sorted(d.glob("soln0*.dat"))
    assert len(files) == 2, files
    return [read_dat(f)[1] for f in files]


def test_supervisor_restarts_after_worker_crash_bit_identical(tmp_cwd, capfd):
    (tmp_cwd / "input.dat").write_text("16 0.25 0.05 2.0 8 1\n")

    # uninterrupted reference run (same code path -> bit-identity is exact)
    assert main(["launch", "-n", "2", *_RUN,
                 "--checkpoint-dir", "ck_clean"]) == 0
    clean = _soln_shards(tmp_cwd)
    for f in tmp_cwd.glob("soln0*.dat"):
        f.unlink()
    capfd.readouterr()

    # worker 1 dies at step 4; the supervisor must kill the blocked
    # survivor, validate checkpoints, and relaunch with resume
    assert main(["launch", "-n", "2", "--max-restarts", "2", *_RUN,
                 "--checkpoint-dir", "ck_chaos",
                 "--inject", "crash@4:proc=1"]) == 0
    healed = _soln_shards(tmp_cwd)
    for c, h in zip(clean, healed):
        np.testing.assert_array_equal(c, h)

    out, err = capfd.readouterr()
    assert "launch: worker 1 exited rc=43" in err      # the injected death
    assert '"event": "launch_restart"' in err          # structured record
    # the crash at step 4 lands BEFORE that boundary's checkpoint write, so
    # the newest WORLD-COMPLETE durable step is 2 (worker 0 may hold a
    # step-4 file; the agreement pulls everyone down to the common step)
    assert '"resume_step": 2' in err
    assert "resumed from shard checkpoints at step 2" in out
    # both processes' shard checkpoints exist for the resume step
    names = {p.name for p in (tmp_cwd / "ck_chaos").glob("*.npz")}
    assert {"heat_shards_step00000002.proc0000.npz",
            "heat_shards_step00000002.proc0001.npz"} <= names


def test_corrupt_newest_shard_checkpoint_falls_back(tmp_cwd, capfd):
    """Resume integrity over a real world: damage the newest shard file of
    one process; the relaunch must quarantine it, agree on the next-older
    step, and still produce the identical field."""
    (tmp_cwd / "input.dat").write_text("16 0.25 0.05 2.0 8 1\n")
    assert main(["launch", "-n", "2", *_RUN]) == 0
    clean = _soln_shards(tmp_cwd)
    for f in tmp_cwd.glob("soln0*.dat"):
        f.unlink()
    ck = tmp_cwd / "checkpoints"
    newest = ck / "heat_shards_step00000008.proc0000.npz"
    newest.write_bytes(newest.read_bytes()[:120])  # torn write / bitrot
    capfd.readouterr()

    assert main(["launch", "-n", "2", *_RUN]) == 0
    out, _ = capfd.readouterr()
    # proc 0 quarantined its torn step-8 file and offered step 6; the
    # job-wide agreement pulled proc 1 (which still had a good step 8)
    # down to 6 with it
    assert (ck / "heat_shards_step00000008.proc0000.npz.corrupt").exists()
    assert "resumed from shard checkpoints at step 6" in out
    healed = _soln_shards(tmp_cwd)
    for c, h in zip(clean, healed):
        np.testing.assert_array_equal(c, h)
