"""Chaos end-to-end: the self-healing launch supervisor over a REAL
two-process world (ISSUE 2 acceptance). An injected mid-run worker crash
must restart the world, resume from the agreed shard checkpoint, and finish
bit-identical to an uninterrupted run; a corrupted newest checkpoint must
fall back one step with the bad file quarantined.

Marked ``slow`` (multi-process worlds spawn jax interpreters; ~15 s each)
so tier-1 (`-m 'not slow'`) keeps its timeout — run explicitly with
``pytest -m slow tests/test_chaos.py``.
"""

import numpy as np
import pytest

from heat_tpu.cli import main
from heat_tpu.io import read_dat

pytestmark = pytest.mark.slow

_RUN = ["run", "--backend", "sharded", "--dtype", "float64", "--mesh", "2x1",
        "--checkpoint-every", "2", "--async-io", "off"]
# --async-io off: the crash must land AFTER the boundary's checkpoint is
# durable, deterministically — the async writer would race the injected
# os._exit and make the resume step nondeterministic (fine in production,
# wrong for a bit-identity acceptance test).


@pytest.fixture(autouse=True)
def _fast_backoff(monkeypatch):
    monkeypatch.setenv("HEAT_TPU_RESTART_BACKOFF_S", "0.05")


def _soln_shards(d):
    files = sorted(d.glob("soln0*.dat"))
    assert len(files) == 2, files
    return [read_dat(f)[1] for f in files]


def test_supervisor_restarts_after_worker_crash_bit_identical(tmp_cwd, capfd):
    (tmp_cwd / "input.dat").write_text("16 0.25 0.05 2.0 8 1\n")

    # uninterrupted reference run (same code path -> bit-identity is exact)
    assert main(["launch", "-n", "2", *_RUN,
                 "--checkpoint-dir", "ck_clean"]) == 0
    clean = _soln_shards(tmp_cwd)
    for f in tmp_cwd.glob("soln0*.dat"):
        f.unlink()
    capfd.readouterr()

    # worker 1 dies at step 4; the supervisor must kill the blocked
    # survivor, validate checkpoints, and relaunch with resume
    assert main(["launch", "-n", "2", "--max-restarts", "2", *_RUN,
                 "--checkpoint-dir", "ck_chaos",
                 "--inject", "crash@4:proc=1"]) == 0
    healed = _soln_shards(tmp_cwd)
    for c, h in zip(clean, healed):
        np.testing.assert_array_equal(c, h)

    out, err = capfd.readouterr()
    assert "launch: worker 1 exited rc=43" in err      # the injected death
    assert '"event": "launch_restart"' in err          # structured record
    # the crash at step 4 lands BEFORE that boundary's checkpoint write, so
    # the newest WORLD-COMPLETE durable step is 2 (worker 0 may hold a
    # step-4 file; the agreement pulls everyone down to the common step)
    assert '"resume_step": 2' in err
    assert "resumed from shard checkpoints at step 2" in out
    # both processes' shard checkpoints exist for the resume step
    names = {p.name for p in (tmp_cwd / "ck_chaos").glob("*.npz")}
    assert {"heat_shards_step00000002.proc0000.npz",
            "heat_shards_step00000002.proc0001.npz"} <= names


def test_serve_chaos_wave_quarantine_watchdog_e2e(tmp_cwd, capsys):
    """Serve per-lane fault domains e2e (ISSUE 5), full-fidelity: a
    24-request wave with ~10% lane-nan poison drains with every healthy
    request ok and bit-identical to a clean run of the same wave; a
    rollback rerun recovers the poisoned requests too; and a fetch-hang
    beyond the watchdog still exits with a record for every request."""
    import json

    from heat_tpu.backends import solve
    from heat_tpu.config import HeatConfig
    from heat_tpu.runtime import faults

    reqs = tmp_cwd / "reqs.jsonl"
    lines = []
    for i in range(24):
        n = (16, 24, 32)[i % 3]
        lines.append({"id": f"r{i}", "n": n, "ntime": 48 + 8 * (i % 2),
                      "dtype": "float64"})
    reqs.write_text("".join(json.dumps(d) + "\n" for d in lines))
    poisoned = {"r9", "r19"}
    inject = ",".join(f"lane-nan@20:req={r}" for r in sorted(poisoned))
    base = ["serve", "--requests", "reqs.jsonl", "--buckets", "32",
            "--chunk", "8", "--lanes", "4"]

    def records(out):
        return {r["id"]: r for r in
                (json.loads(l) for l in out.splitlines()
                 if l.startswith("{") and '"serve_request"' in l)}

    faults.reset()
    assert main([*base, "--out-dir", "clean"]) == 0
    clean = records(capsys.readouterr().out)
    assert all(r["status"] == "ok" for r in clean.values())

    faults.reset()
    assert main([*base, "--out-dir", "chaos", "--inject", inject]) == 1
    chaos = records(capsys.readouterr().out)
    assert len(chaos) == 24
    for rid, rec in chaos.items():
        if rid in poisoned:
            assert rec["status"] == "nonfinite", rec
            assert not (tmp_cwd / "chaos" / f"{rid}.npz").exists()
        else:
            assert rec["status"] == "ok", rec
            with np.load(tmp_cwd / "chaos" / f"{rid}.npz") as zc, \
                    np.load(tmp_cwd / "clean" / f"{rid}.npz") as zl:
                np.testing.assert_array_equal(zc["T"], zl["T"])

    # rollback rerun: the fire-once poison is transient, so every request
    # recovers — the poisoned ones bit-identical to their solo runs
    faults.reset()
    assert main([*base, "--out-dir", "healed", "--inject", inject,
                 "--serve-on-nan", "rollback"]) == 0
    healed = records(capsys.readouterr().out)
    assert all(r["status"] == "ok" for r in healed.values())
    for rid in poisoned:
        d = next(l for l in lines if l["id"] == rid)
        solo = solve(HeatConfig(n=d["n"], ntime=d["ntime"],
                                dtype="float64")).T
        with np.load(tmp_cwd / "healed" / f"{rid}.npz") as z:
            np.testing.assert_array_equal(z["T"], solo)

    # wedged boundary fetch: the watchdog fails the group cleanly and the
    # CLI still exits (rc=1, not a hang) with a record for every request
    faults.reset()
    assert main([*base, "--inject", "fetch-hang:ms=3000",
                 "--fetch-watchdog", "0.5"]) == 1
    out = capsys.readouterr().out
    wedged = records(out)
    assert len(wedged) == 24
    assert all(r["status"] == "error"
               and "fetch-watchdog" in r["error"] for r in wedged.values())
    assert "1 watchdog timeout(s)" in out


def test_corrupt_newest_shard_checkpoint_falls_back(tmp_cwd, capfd):
    """Resume integrity over a real world: damage the newest shard file of
    one process; the relaunch must quarantine it, agree on the next-older
    step, and still produce the identical field."""
    (tmp_cwd / "input.dat").write_text("16 0.25 0.05 2.0 8 1\n")
    assert main(["launch", "-n", "2", *_RUN]) == 0
    clean = _soln_shards(tmp_cwd)
    for f in tmp_cwd.glob("soln0*.dat"):
        f.unlink()
    ck = tmp_cwd / "checkpoints"
    newest = ck / "heat_shards_step00000008.proc0000.npz"
    newest.write_bytes(newest.read_bytes()[:120])  # torn write / bitrot
    capfd.readouterr()

    assert main(["launch", "-n", "2", *_RUN]) == 0
    out, _ = capfd.readouterr()
    # proc 0 quarantined its torn step-8 file and offered step 6; the
    # job-wide agreement pulled proc 1 (which still had a good step 8)
    # down to 6 with it
    assert (ck / "heat_shards_step00000008.proc0000.npz.corrupt").exists()
    assert "resumed from shard checkpoints at step 6" in out
    healed = _soln_shards(tmp_cwd)
    for c, h in zip(clean, healed):
        np.testing.assert_array_equal(c, h)


def test_serve_chaos_under_lockcheck_zero_inversions(tmp_cwd, capsys,
                                                     monkeypatch):
    """ISSUE 11: the full fault-injected serve surface — lane-nan
    quarantine, rollback heal, fetch-watchdog group failure — under the
    armed lock-order watchdog (HEAT_TPU_LOCKCHECK=1). Every lock
    acquisition across the scheduler, writer, observatory, and tracer
    threads must respect the documented gateway < engine < observatory
    order: zero inversions, with the engine->observatory edges actually
    exercised (the watchdog saw real cross-thread traffic, not an idle
    engine)."""
    import json

    from heat_tpu.runtime import debug, faults

    monkeypatch.setenv("HEAT_TPU_LOCKCHECK", "1")
    debug.reset_lock_order_stats()

    reqs = tmp_cwd / "reqs.jsonl"
    lines = [{"id": f"r{i}", "n": (16, 24, 32)[i % 3], "ntime": 40,
              "dtype": "float64"} for i in range(12)]
    reqs.write_text("".join(json.dumps(d) + "\n" for d in lines))
    base = ["serve", "--requests", "reqs.jsonl", "--buckets", "32",
            "--chunk", "8", "--lanes", "4"]

    # quarantine + rollback heal under the armed watchdog
    faults.reset()
    assert main([*base, "--inject", "lane-nan@16:req=r5",
                 "--serve-on-nan", "rollback"]) == 0
    recs = {r["id"]: r for r in
            (json.loads(l) for l in capsys.readouterr().out.splitlines()
             if l.startswith("{") and '"serve_request"' in l)}
    assert all(r["status"] == "ok" for r in recs.values())

    # wedged fetch -> watchdog group failure, still no lock inversion
    faults.reset()
    assert main([*base, "--inject", "fetch-hang:ms=2000",
                 "--fetch-watchdog", "0.4"]) == 1
    capsys.readouterr()

    stats = debug.lock_order_stats()
    assert stats["violations"] == [], stats["violations"]
    assert any(e[0] == "engine" and e[1].startswith("observatory")
               for e in stats["edges"])


def test_serve_chaos_under_racecheck_zero_findings(tmp_cwd, capsys,
                                                   monkeypatch):
    """ISSUE 14: the same fault-injected serve surface — lane-nan
    quarantine with rollback heal, then a wedged fetch tripping the
    group watchdog — under the armed race sanitizer
    (HEAT_TPU_RACECHECK=1). Every cross-thread field write on the
    instrumented engine, snapshot writer, tracer, and gateway objects
    must keep a non-empty candidate lockset (or be an exempted,
    allow-marked pattern): zero findings, and the sanitizer must have
    actually instrumented the stack (not silently disarmed)."""
    import json

    from heat_tpu.runtime import debug, faults

    monkeypatch.setenv("HEAT_TPU_RACECHECK", "1")
    debug.reset_race_stats()

    reqs = tmp_cwd / "reqs.jsonl"
    lines = [{"id": f"r{i}", "n": (16, 24, 32)[i % 3], "ntime": 40,
              "dtype": "float64"} for i in range(12)]
    reqs.write_text("".join(json.dumps(d) + "\n" for d in lines))
    base = ["serve", "--requests", "reqs.jsonl", "--buckets", "32",
            "--chunk", "8", "--lanes", "4"]

    # quarantine + rollback heal under the armed sanitizer (a finding
    # raises RaceError inside the serve loop and fails the run)
    faults.reset()
    assert main([*base, "--inject", "lane-nan@16:req=r5",
                 "--serve-on-nan", "rollback"]) == 0
    recs = {r["id"]: r for r in
            (json.loads(l) for l in capsys.readouterr().out.splitlines()
             if l.startswith("{") and '"serve_request"' in l)}
    assert all(r["status"] == "ok" for r in recs.values())

    # wedged fetch -> watchdog group failure, still race-clean
    faults.reset()
    assert main([*base, "--inject", "fetch-hang:ms=2000",
                 "--fetch-watchdog", "0.4"]) == 1
    capsys.readouterr()

    stats = debug.race_stats()
    assert stats["findings"] == [], stats["findings"]
    assert stats["instrumented"] >= 2


def test_serve_cache_chaos_corrupt_and_stale_quarantine_recompute(tmp_cwd,
                                                                  capsys):
    """Solve-cache fault domains e2e (ISSUE 19): a warm cache whose
    consulted entry is bit-rotted (``cache-corrupt``) or mis-filed
    (``cache-stale``) must never be served — validation quarantines the
    damage to ``*.corrupt`` with a structured ``cache_quarantined``
    record, the request recomputes bit-identical to the clean run, and
    the healthy rerun afterwards full-hits the republished entry."""
    import json

    from heat_tpu.runtime import faults

    reqs = tmp_cwd / "reqs.jsonl"
    lines = [{"id": f"r{i}", "n": 16, "ntime": 40, "dtype": "float64"}
             for i in range(3)]
    reqs.write_text("".join(json.dumps(d) + "\n" for d in lines))
    base = ["serve", "--requests", "reqs.jsonl", "--buckets", "16",
            "--chunk", "8", "--lanes", "2", "--cache", "on",
            "--cache-dir", "cache"]

    def records(out):
        return {r["id"]: r for r in
                (json.loads(l) for l in out.splitlines()
                 if l.startswith("{") and '"serve_request"' in l)}

    # cold run populates (all consults precede the writeback), warm run
    # full-hits every request from the published entry
    faults.reset()
    assert main([*base, "--out-dir", "cold"]) == 0
    capsys.readouterr()
    faults.reset()
    assert main([*base, "--out-dir", "warm"]) == 0
    warm = records(capsys.readouterr().out)
    assert all(r["status"] == "ok" for r in warm.values())
    assert all(r["cached"] for r in warm.values())

    for spec, reason_frag in (("cache-corrupt", "hash mismatch"),
                              ("cache-stale", "stale")):
        for f in tmp_cwd.glob("cache/*.corrupt"):
            f.unlink()
        # the damaged consult must recompute, not serve the bad entry
        faults.reset()
        out_dir = f"chaos-{spec}"
        assert main([*base, "--out-dir", out_dir,
                     "--inject", spec]) == 0
        out = capsys.readouterr().out
        chaos = records(out)
        assert all(r["status"] == "ok" for r in chaos.values())
        quarantines = [json.loads(l) for l in out.splitlines()
                       if l.startswith("{")
                       and '"cache_quarantined"' in l]
        assert len(quarantines) == 1, out
        assert reason_frag in quarantines[0]["reason"]
        assert len(list(tmp_cwd.glob("cache/*.corrupt"))) == 2
        for rid in chaos:
            with np.load(tmp_cwd / out_dir / f"{rid}.npz") as zc, \
                    np.load(tmp_cwd / "warm" / f"{rid}.npz") as zw:
                np.testing.assert_array_equal(zc["T"], zw["T"])
        # the recompute republished the entry: a healthy rerun full-hits
        faults.reset()
        assert main([*base, "--out-dir", f"heal-{spec}"]) == 0
        healed = records(capsys.readouterr().out)
        assert all(r["cached"] for r in healed.values()), healed
