"""AOT topology compile: the overlap schedule property, pinned chiplessly.

``jax.experimental.topologies`` compiles genuine multi-chip TPU
executables on a CPU-only host (XLA:TPU + Mosaic ship in libtpu), which
makes the overlap exchange's whole point — kernel work scheduled inside
the async collective-permute flight window — a testable property rather
than an on-hardware observation. Skips cleanly where no TPU AOT compiler
is available."""

import jax
import jax.numpy as jnp
import pytest

# Topology-AOT executables can't round-trip the persistent compile cache
# (read side: libtpu's "DeserializeLoadedExecutable not implemented"; write
# side: a CompileOnlyPyClient's Executable isn't serializable); jax
# surfaces each failed read/write as a UserWarning per compile and then
# compiles normally — harmless here, and consistent with the measured fact
# that Mosaic topology compiles bypass the persistent cache entirely
# (TROUBLESHOOTING.md "Compiles"). Filter exactly those messages so
# `pytest -q` stays clean (VERDICT r5 #8); any OTHER warning still
# surfaces.
pytestmark = pytest.mark.filterwarnings(
    "ignore:Error (reading|writing) persistent compilation cache entry"
    ":UserWarning")

from heat_tpu.backends.sharded import make_padded_carry_machinery  # noqa: E402
from heat_tpu.config import HeatConfig
from heat_tpu.ops.pallas_stencil import force_compiled_kernels


@pytest.fixture(scope="module")
def topo_mesh():
    from jax.experimental import topologies

    try:
        topo = topologies.get_topology_desc("v5e:2x2", "tpu")
    except Exception as e:  # no libtpu AOT compiler on this host
        pytest.skip(f"TPU AOT topology compiler unavailable: {e}")
    return topologies.make_mesh(topo, (2, 2), ("x", "y"))


@pytest.fixture(autouse=True)
def _no_x64():
    """The suite runs with x64 on (f64 parity oracle); Mosaic lowering is
    an f32/i32 world — under x64 the roll amounts trace as i64 and fail
    op verification. TPU runs never enable x64, so scope it off here."""
    import jax

    jax.config.update("jax_enable_x64", False)
    jax.clear_caches()
    yield
    jax.config.update("jax_enable_x64", True)
    jax.clear_caches()


def _compiled_text(topo_mesh, exchange: str) -> str:
    from jax.sharding import NamedSharding, PartitionSpec as P

    n, fuse, steps = 256, 4, 8
    cfg = HeatConfig(n=n, ntime=steps, dtype="float32", backend="sharded",
                     mesh_shape=(2, 2), fuse_steps=fuse, exchange=exchange,
                     local_kernel="pallas")
    with force_compiled_kernels():
        _, advance, _ = make_padded_carry_machinery(cfg, topo_mesh)
        struct = jax.ShapeDtypeStruct(
            (n + 4 * fuse, n + 4 * fuse), jnp.float32,
            sharding=NamedSharding(topo_mesh, P("x", "y")))
        return advance.lower(struct, steps).compile().as_text()


def _census(txt: str) -> dict:
    # ONE definition of the schedule census — the lab's
    import sys
    from pathlib import Path

    sys.path.insert(0, str(Path(__file__).resolve().parent.parent
                           / "benchmarks"))
    from topology_schedule import schedule_census

    return schedule_census(txt)


def test_overlap_schedules_kernel_inside_collective_flight(topo_mesh):
    c = _census(_compiled_text(topo_mesh, "overlap"))
    assert c["async_pairs"] >= 4  # 2 directions x 2 axes at minimum
    assert c["unmatched_dones"] == 0
    # the interior kernel has no data edge to the collectives; the
    # latency-hiding scheduler must exploit that
    assert c["kernels_in_flight"] >= 1


def test_indep_schedule_is_strictly_sequential(topo_mesh):
    c = _census(_compiled_text(topo_mesh, "indep"))
    assert c["async_pairs"] >= 4
    assert c["unmatched_dones"] == 0
    assert c["kernels_in_flight"] == 0  # exchange-then-kernel
