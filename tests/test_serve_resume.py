"""Zero-downtime serving (ISSUE 17): engine-state checkpoint, crash-safe
``serve --resume``, and drain-as-handoff.

The load-bearing contracts:

- a kill at any checkpointed boundary is invisible in the results:
  resuming from the surviving generation yields npz BYTE-IDENTICAL to
  the uninterrupted run — packed and mega placements, dispatch depths
  0 and 2 (the consistent cut is the empty-pipeline boundary, and the
  lane reseed rides the same ``load_lane`` path ``maybe_grow``
  transplants already proved bit-exact);
- queued requests re-enter in original policy order (fifo golden trace
  + edf), and usage billing resumes from the stamped partials with no
  step double-billed;
- a corrupt manifest is quarantined loudly and discovery falls back one
  generation;
- ``until=steady`` lanes resume with their EWMA/prediction state
  re-seeded, retiring at the same boundary as the uninterrupted run;
- ``POST /drainz?handoff=1`` checkpoints at the next empty-pipeline cut
  without waiting for lanes to finish, and a second engine picks the
  work up over HTTP.
"""

import json
import os
import re
import signal
import subprocess
import sys
from pathlib import Path

import numpy as np
import pytest

from heat_tpu.config import HeatConfig
from heat_tpu.runtime import checkpoint as ckpt_mod
from heat_tpu.runtime import faults
from heat_tpu.serve import Engine, ServeConfig
from heat_tpu.serve.resume import resume_engine


@pytest.fixture(autouse=True)
def _fresh_faults():
    faults.reset()
    yield
    faults.reset()


def quiet(**kw) -> ServeConfig:
    kw.setdefault("emit_records", False)
    kw.setdefault("lanes", 2)
    kw.setdefault("chunk", 8)
    kw.setdefault("buckets", (32, 48))
    return ServeConfig(**kw)


# mixed sizes/steps: 6 requests over 2 lanes forces continuous-batching
# admissions, step counts not all chunk multiples exercise tails (kept
# small — this file rides the tier-1 wall budget)
WAVE = [
    HeatConfig(n=17, ntime=21, dtype="float64", bc="edges", ic="hat"),
    HeatConfig(n=32, ntime=34, dtype="float64", bc="ghost", ic="uniform"),
    HeatConfig(n=24, ntime=28, dtype="float64", bc="edges", ic="hat_small",
               nu=0.1),
    HeatConfig(n=40, ntime=12, dtype="float64", bc="edges", ic="hat"),
    HeatConfig(n=20, ntime=40, dtype="float64", bc="ghost", ic="hat",
               bc_value=2.5),
    HeatConfig(n=17, ntime=19, dtype="float64", bc="ghost", ic="hat_half"),
]


def run_wave(tmp_path, tag, cfgs, engine=None, ids=None, **kw):
    out = tmp_path / tag
    eng = engine or Engine(quiet(out_dir=str(out),
                                 engine_ckpt_dir=str(tmp_path
                                                     / f"{tag}-ckpt"),
                                 **kw))
    for i, cfg in enumerate(cfgs):
        eng.submit(cfg, request_id=(ids[i] if ids else f"r{i}"))
    return eng, {r["id"]: r for r in eng.results()}


def kill_after(ckdir: Path, gen: int, outdir: Path = None):
    """Simulate a SIGKILL right after generation ``gen`` became durable:
    delete every newer generation, and (when ``outdir`` is given) every
    result file the survivor does not list as done — exactly the on-disk
    state the FIFO writer ordering guarantees."""
    man = json.loads(
        (ckdir / ckpt_mod.ENGINE_MANIFEST_FMT.format(gen=gen)).read_text())
    for p in list(ckdir.glob("engine_gen*")):
        if int(re.search(r"gen(\d+)", p.name).group(1)) > gen:
            p.unlink()
    if outdir is not None and outdir.is_dir():
        done = set(man["done"])
        for p in list(outdir.glob("*.npz")):
            if p.stem not in done:
                p.unlink()
    return man


# --- kill-at-boundary -> resume byte-identity --------------------------------


@pytest.mark.parametrize("depth", [0, 2])
def test_resume_bit_identity_packed(tmp_path, depth):
    """Acceptance: kill at the first checkpointed boundary mid-wave,
    resume, and every npz — survivors and re-served alike — is
    byte-identical to the uninterrupted run, at depths 0 and 2."""
    _, golden = run_wave(tmp_path, "golden", WAVE, dispatch_depth=depth)
    run_wave(tmp_path, "killed", WAVE, dispatch_depth=depth,
             engine_ckpt_interval=3)
    ck = tmp_path / "killed-ckpt"
    man = kill_after(ck, 1, tmp_path / "killed")
    assert man["inflight"], "cut must land mid-wave to prove anything"

    eng = Engine(quiet(out_dir=str(tmp_path / "resumed"),
                       dispatch_depth=depth, engine_ckpt_dir=str(ck)))
    skip = resume_engine(eng, ck)
    assert skip == {f"r{i}" for i in range(len(WAVE))}
    resumed = {r["id"]: r for r in eng.results()}
    assert all(r["status"] == "ok" for r in resumed.values())
    assert all(r["resumed"] for r in resumed.values())

    for rid in golden:
        a = tmp_path / "golden" / f"{rid}.npz"
        b = tmp_path / "killed" / f"{rid}.npz"
        if not b.exists():
            b = tmp_path / "resumed" / f"{rid}.npz"
        assert a.read_bytes() == b.read_bytes(), rid


def test_resume_bit_identity_pallas_kernel(tmp_path):
    """The lane-kernel knob survives resume: a float32 wave under
    lane_kernel='pallas' (falls back to the bit-identical XLA oracle off
    TPU) resumes byte-identically."""
    wave = [c.with_(dtype="float32") for c in WAVE[:3]]
    _, golden = run_wave(tmp_path, "golden", wave, lane_kernel="pallas")
    run_wave(tmp_path, "killed", wave, lane_kernel="pallas",
             engine_ckpt_interval=2)
    ck = tmp_path / "killed-ckpt"
    kill_after(ck, 1, tmp_path / "killed")
    eng = Engine(quiet(out_dir=str(tmp_path / "resumed"),
                       lane_kernel="pallas", engine_ckpt_dir=str(ck)))
    resume_engine(eng, ck)
    resumed = {r["id"]: r for r in eng.results()}
    assert all(r["status"] == "ok" for r in resumed.values())
    for rid in golden:
        a = tmp_path / "golden" / f"{rid}.npz"
        b = tmp_path / "killed" / f"{rid}.npz"
        if not b.exists():
            b = tmp_path / "resumed" / f"{rid}.npz"
        assert a.read_bytes() == b.read_bytes(), rid


# n=16 overflows the (8,) bucket table and divides the auto 4x2 mesh of
# the 8-device harness (the test_serve_mega.py shape)
MEGA_CFG = HeatConfig(n=16, ntime=37, dtype="float64", bc="edges")


@pytest.mark.parametrize("depth", [0, 2])
def test_resume_bit_identity_mega(tmp_path, depth):
    """A mesh-spanning mega-lane killed mid-solve resumes bit-exactly:
    the owned-cell crop -> seed round trip at a chunk boundary is the
    same one the mega engine's rollback path already rides."""
    kw = dict(buckets=(8,), dispatch_depth=depth)
    _, golden = run_wave(tmp_path, "golden", [MEGA_CFG], ids=["big"], **kw)
    assert golden["big"]["placement"] == "mega"
    run_wave(tmp_path, "killed", [MEGA_CFG], ids=["big"],
             engine_ckpt_interval=2, **kw)
    ck = tmp_path / "killed-ckpt"
    man = kill_after(ck, 1, tmp_path / "killed")
    assert [e["placement"] for e in man["inflight"]] == ["mega"]
    assert 0 < man["inflight"][0]["remaining"] < MEGA_CFG.ntime

    eng = Engine(quiet(out_dir=str(tmp_path / "resumed"),
                       engine_ckpt_dir=str(ck), **kw))
    resume_engine(eng, ck)
    resumed = {r["id"]: r for r in eng.results()}
    assert resumed["big"]["status"] == "ok"
    assert ((tmp_path / "golden" / "big.npz").read_bytes()
            == (tmp_path / "resumed" / "big.npz").read_bytes())


# --- policy-order preservation across resume ---------------------------------


def _order_wave():
    """9 tiny same-bucket requests, deadlines deliberately anti-submit-
    order so edf and fifo produce DIFFERENT admission traces."""
    cfgs, deadlines = [], []
    for i in range(9):
        cfgs.append(HeatConfig(n=16, ntime=24 + 8 * (i % 3),
                               dtype="float64"))
        deadlines.append(1e6 - 1e4 * i)   # later submits = tighter
    return cfgs, deadlines


@pytest.mark.parametrize("policy", ["fifo", "edf"])
def test_resume_preserves_policy_order(tmp_path, policy):
    """Queued requests recovered from a manifest re-enter in the SAME
    relative order the uninterrupted engine would have admitted them:
    the manifest replays submits in original seq order and the policy
    queue re-sorts, so fifo keeps submit order and edf keeps deadline
    order — bit-for-bit against the golden admission trace."""
    cfgs, deadlines = _order_wave()

    def submit_all(eng):
        for i, cfg in enumerate(cfgs):
            eng.submit(cfg, request_id=f"r{i}", deadline_ms=deadlines[i])

    golden_eng = Engine(quiet(lanes=1, buckets=(16,), policy=policy))
    submit_all(golden_eng)
    golden_eng.results()
    golden_trace = golden_eng.admission_trace

    killed_eng = Engine(quiet(lanes=1, buckets=(16,), policy=policy,
                              engine_ckpt_interval=2,
                              engine_ckpt_dir=str(tmp_path / "ck")))
    submit_all(killed_eng)
    killed_eng.results()
    man = kill_after(tmp_path / "ck", 1)
    queued_ids = [e["id"] for e in man["queued"]]
    assert len(queued_ids) >= 3, "cut must leave a real queue"

    resumed_eng = Engine(quiet(lanes=1, buckets=(16,), policy=policy))
    resume_engine(resumed_eng, tmp_path / "ck")
    resumed_eng.results()
    resumed_order = [rid for rid in resumed_eng.admission_trace
                     if rid in set(queued_ids)]
    golden_order = [rid for rid in golden_trace
                    if rid in set(queued_ids)]
    assert resumed_order == golden_order


# --- usage-ledger reconciliation ---------------------------------------------


def test_resume_usage_partials_no_double_billing(tmp_path):
    """Billing spans incarnations exactly once: the resumed record's
    steps/chunks equal the uninterrupted run's (the countdown and the
    cumulative chunk counter restore from the manifest), its lane_s
    folds the checkpointed partial in, and the ledger still reconciles
    totals == sum of per-record stamps."""
    _, golden = run_wave(tmp_path, "golden", WAVE)
    run_wave(tmp_path, "killed", WAVE, engine_ckpt_interval=3)
    ck = tmp_path / "killed-ckpt"
    man = kill_after(ck, 1, tmp_path / "killed")
    partials = {e["id"]: e["lane_s"] for e in man["inflight"]}

    eng = Engine(quiet(out_dir=str(tmp_path / "resumed"), prof=True,
                       engine_ckpt_dir=str(ck)))
    resume_engine(eng, ck)
    resumed = {r["id"]: r for r in eng.results()}
    for rid, rec in resumed.items():
        g = golden[rid]
        assert rec["usage"]["steps"] == g["usage"]["steps"], rid
        assert rec["usage"]["chunks"] == g["usage"]["chunks"], rid
        assert rec["steps_done"] == g["steps_done"], rid
        if rid in partials:
            # the stamped partial is a floor on the resumed wall
            assert rec["usage"]["lane_s"] >= partials[rid] - 1e-9, rid
    # the engine summary carries the recovery count
    s = eng.summary()
    assert s["serve_resumed"] == len(resumed)
    # ledger reconciliation: totals == sum of the per-record stamps
    ledger = eng.prof.ledger.snapshot()
    assert ledger["totals"]["steps"] == sum(
        r["usage"]["steps"] for r in resumed.values())
    assert ledger["totals"]["requests"] == len(resumed)


# --- corrupt manifest: quarantine + fall back one generation -----------------


def test_corrupt_manifest_quarantines_and_falls_back(tmp_path, capsys):
    """A damaged newest manifest must not take resume down: discovery
    quarantines it to *.corrupt with a loud line and restores from the
    previous generation — which still yields byte-identical results
    (just with more steps to re-serve)."""
    _, golden = run_wave(tmp_path, "golden", WAVE)
    run_wave(tmp_path, "killed", WAVE, engine_ckpt_interval=2)
    ck = tmp_path / "killed-ckpt"
    kill_after(ck, 2, tmp_path / "killed")
    newest = ck / ckpt_mod.ENGINE_MANIFEST_FMT.format(gen=2)
    raw = bytearray(newest.read_bytes())
    raw[len(raw) // 2] ^= 0xFF
    newest.write_bytes(bytes(raw))
    # the fall-back loses gen-2's done-set authority, so re-serve from
    # the gen-1 view: wipe result files gen 1 does not list as done
    # (keeping the corrupted gen-2 manifest in place for discovery)
    man1 = json.loads(
        (ck / ckpt_mod.ENGINE_MANIFEST_FMT.format(gen=1)).read_text())
    for p in (tmp_path / "killed").glob("*.npz"):
        if p.stem not in set(man1["done"]):
            p.unlink()

    eng = Engine(quiet(out_dir=str(tmp_path / "resumed"),
                       engine_ckpt_dir=str(ck)))
    resume_engine(eng, ck)
    out = capsys.readouterr().out + capsys.readouterr().err
    assert "quarantin" in out
    assert "falling back one generation" in out
    assert (ck / (newest.name + ".corrupt")).exists()
    assert eng._engine_ckpt_gen == 1   # restored from the survivor

    resumed = {r["id"]: r for r in eng.results()}
    assert all(r["status"] == "ok" for r in resumed.values())
    for rid in golden:
        a = tmp_path / "golden" / f"{rid}.npz"
        b = tmp_path / "killed" / f"{rid}.npz"
        if not b.exists():
            b = tmp_path / "resumed" / f"{rid}.npz"
        assert a.read_bytes() == b.read_bytes(), rid


def test_ckpt_manifest_corrupt_fault_spec(tmp_path):
    """The ckpt-manifest-corrupt@N injection damages the Nth generation
    AT WRITE (the chaos lab's knob for the quarantine path): resume must
    land on the last clean generation."""
    run_wave(tmp_path, "killed", WAVE, engine_ckpt_interval=3,
             inject="ckpt-manifest-corrupt@2")
    ck = tmp_path / "killed-ckpt"
    gens = sorted(int(re.search(r"gen(\d+)", p.name).group(1))
                  for p in ck.glob("engine_gen*.json"))
    assert 2 in gens
    # make the damaged generation the newest, as a kill right after it
    # would have: discovery must trip on it, not sail past
    for p in list(ck.glob("engine_gen*")):
        if int(re.search(r"gen(\d+)", p.name).group(1)) > 2:
            p.unlink()
    eng = Engine(quiet(engine_ckpt_dir=str(ck)))
    resume_engine(eng, ck)
    assert eng._engine_ckpt_gen == 1
    assert (ck / (ckpt_mod.ENGINE_MANIFEST_FMT.format(gen=2)
                  + ".corrupt")).exists()


def test_fingerprint_mismatch_is_loud(tmp_path):
    """A manifest whose entry fingerprint does not match its own config
    must refuse to resume — never silently continue different physics."""
    run_wave(tmp_path, "killed", WAVE[:2], engine_ckpt_interval=1)
    ck = tmp_path / "killed-ckpt"
    man = kill_after(ck, 1)
    assert man["inflight"] or man["queued"]
    path = ck / ckpt_mod.ENGINE_MANIFEST_FMT.format(gen=1)
    man = json.loads(path.read_text())
    rows = man["inflight"] + man["queued"]
    rows[0]["cfg"]["nu"] = rows[0]["cfg"]["nu"] * 2   # different physics
    path.write_text(json.dumps(man, sort_keys=True))
    eng = Engine(quiet())
    with pytest.raises(ValueError, match="fingerprint mismatch"):
        resume_engine(eng, ck)


def test_resume_empty_dir_is_loud_fresh_start(tmp_path, capsys):
    eng = Engine(quiet())
    assert resume_engine(eng, tmp_path / "nowhere") == set()
    assert "starting fresh" in capsys.readouterr().out


# --- until=steady lanes resume with prediction state -------------------------


def test_steady_lane_resumes_with_reseeded_ewma(tmp_path):
    """An until=steady lane killed mid-decay retires at the SAME
    boundary with the SAME bytes after resume: the residual EWMA and
    the rate-fuser observations ride the manifest (a cold restart of
    the observatory would re-warm the EWMA and exit late)."""
    cfg = HeatConfig(n=12, ntime=160, dtype="float64", bc="edges",
                     ic="sine")
    tol = 2e-3
    gkw = dict(lanes=1, buckets=(16,), chunk=8, out_dir=None,
               keep_fields=True)
    geng = Engine(quiet(**gkw))
    geng.submit(cfg, request_id="s", until="steady", tol=tol)
    golden = {r["id"]: r for r in geng.results()}["s"]
    assert golden["exit"] == "steady"
    assert golden["steps_done"] < cfg.ntime

    keng = Engine(quiet(engine_ckpt_interval=3,
                        engine_ckpt_dir=str(tmp_path / "ck"), **gkw))
    keng.submit(cfg, request_id="s", until="steady", tol=tol)
    keng.results()
    man = kill_after(tmp_path / "ck", 1)
    entry = man["inflight"][0]
    assert entry["numerics"] is not None
    assert entry["numerics"]["resid_ewma"] is not None
    assert 0 < entry["steps_done"] < golden["steps_done"]

    reng = Engine(quiet(**gkw))
    resume_engine(reng, tmp_path / "ck")
    rec = {r["id"]: r for r in reng.results()}["s"]
    assert rec["exit"] == "steady"
    assert rec["steps_done"] == golden["steps_done"]
    assert (np.asarray(rec["T"]).tobytes()
            == np.asarray(golden["T"]).tobytes())


# --- drain-as-handoff + gateway e2e ------------------------------------------


def test_handoff_drain_checkpoints_without_finishing(tmp_path):
    """begin_drain(handoff=True) checkpoints at the next empty-pipeline
    cut WITHOUT waiting for lanes to finish: occupants stay status
    'running' (no terminal records), and the manifest carries them —
    a second engine finishes the work byte-identically."""
    big = [c.with_(ntime=c.ntime * 4) for c in WAVE]   # long enough to
    _, golden_big = run_wave(tmp_path, "goldenb", big)  # catch mid-flight
    ck = tmp_path / "hand-ckpt"
    eng = Engine(quiet(out_dir=str(tmp_path / "hand"),
                       engine_ckpt_interval=1000,  # cadence off the path
                       engine_ckpt_dir=str(ck)))
    eng.start()
    for i, cfg in enumerate(big):
        eng.submit(cfg, request_id=f"r{i}")
    # handoff immediately: lanes are mid-solve (or still queued)
    eng.begin_drain(handoff=True)
    assert eng.shutdown(timeout=120)
    mans = sorted(ck.glob("engine_gen*.json"))
    assert len(mans) == 1
    man = json.loads(mans[0].read_text())
    assert man["reason"] == "handoff"
    # whatever was occupying a lane at the cut is still 'running'
    for e in man["inflight"]:
        rec = eng.poll(e["id"])
        if e["remaining"] < big[int(e["id"][1:])].ntime:
            assert rec["status"] == "running"

    eng2 = Engine(quiet(out_dir=str(tmp_path / "hand2"),
                        engine_ckpt_dir=str(ck)))
    resume_engine(eng2, ck)
    resumed = {r["id"]: r for r in eng2.results()}
    assert all(r["status"] == "ok" for r in resumed.values())
    for i in range(len(big)):
        a = tmp_path / "goldenb" / f"r{i}.npz"
        b = tmp_path / "hand" / f"r{i}.npz"
        if not b.exists():
            b = tmp_path / "hand2" / f"r{i}.npz"
        assert a.read_bytes() == b.read_bytes(), f"r{i}"


def test_gateway_handoff_resume_e2e(tmp_path):
    """The whole zero-downtime story over HTTP: submit through gateway
    A, POST /drainz?handoff=1, bring gateway B up with --resume
    semantics, and collect byte-identical results — plus the resume
    surface on /metrics and /statusz."""
    import urllib.request

    from heat_tpu.serve.gateway import Gateway, render_metrics, \
        render_statusz

    def http(gw, method, path, body=None):
        req = urllib.request.Request(
            f"http://{gw.address}{path}",
            data=body.encode() if body is not None else None,
            method=method)
        resp = urllib.request.urlopen(req, timeout=60)
        return resp.status, [json.loads(l) for l in
                             resp.read().decode().splitlines() if l.strip()]

    _, golden = run_wave(tmp_path, "golden",
                         [c.with_(ntime=c.ntime * 4) for c in WAVE])
    ck = tmp_path / "gw-ckpt"
    engA = Engine(quiet(out_dir=str(tmp_path / "gwA"),
                        engine_ckpt_interval=1000,
                        engine_ckpt_dir=str(ck)))
    gwA = Gateway(engA, "127.0.0.1", 0).start()
    try:
        for i, c in enumerate(WAVE):
            st, _ = http(gwA, "POST", "/v1/solve?wait=0",
                         json.dumps({"id": f"r{i}", "n": c.n,
                                     "ntime": c.ntime * 4,
                                     "dtype": c.dtype, "bc": c.bc,
                                     "bc_value": c.bc_value, "nu": c.nu,
                                     "ic": c.ic}) + "\n")
            assert st == 202   # accepted, not waited on
        st, (d,) = http(gwA, "POST", "/drainz?handoff=1")
        assert st == 200 and d["handoff"] is True
        assert gwA.wait_drained(120)
    finally:
        engA.begin_drain(handoff=True)
        engA.shutdown(timeout=120)
        gwA.close()
    man = json.loads(sorted(ck.glob("engine_gen*.json"))[-1].read_text())
    assert man["reason"] == "handoff"

    engB = Engine(quiet(out_dir=str(tmp_path / "gwB"),
                        engine_ckpt_dir=str(ck)))
    resume_engine(engB, ck)
    gwB = Gateway(engB, "127.0.0.1", 0).start()
    try:
        for i in range(len(WAVE)):
            st, (rec,) = http(gwB, "GET", f"/v1/requests/r{i}")
            assert st == 200
        m = render_metrics(engB)
        assert re.search(r"heat_tpu_serve_resumed_requests_total \d", m)
        assert "heat_tpu_engine_ckpt_generation" in m
        assert "re-admitted from a checkpoint" in render_statusz(engB)
        gwB.request_drain()
        assert gwB.wait_drained(120)
    finally:
        engB.shutdown(timeout=120)
        gwB.close()
    for i in range(len(WAVE)):
        a = tmp_path / "golden" / f"r{i}.npz"
        b = tmp_path / "gwA" / f"r{i}.npz"
        done_at_cut = f"r{i}" in set(man["done"])
        if not done_at_cut:
            b = tmp_path / "gwB" / f"r{i}.npz"
        assert a.read_bytes() == b.read_bytes(), f"r{i}"


# --- engine-kill fault + CLI chaos e2e ---------------------------------------


def test_engine_kill_spec_parses_and_requires_step():
    fs = faults.parse_spec("engine-kill@7")
    assert fs and fs[0].kind == "engine-kill" and fs[0].step == 7
    with pytest.raises(ValueError):
        faults.parse_spec("engine-kill")   # step is the whole point
    # valid with and without a step
    faults.parse_spec("ckpt-manifest-corrupt@2")
    faults.parse_spec("ckpt-manifest-corrupt")


def test_damage_manifest_flips_bytes_once(tmp_path):
    p = tmp_path / "m.json"
    p.write_text(json.dumps({"k": "v" * 64}))
    before = p.read_bytes()
    plan = faults.FaultPlan("ckpt-manifest-corrupt@3")
    plan.damage_manifest(p, 2)          # below the step: untouched
    assert p.read_bytes() == before
    plan.damage_manifest(p, 3)          # at the step: damaged
    assert p.read_bytes() != before
    damaged = p.read_bytes()
    plan.damage_manifest(p, 4)          # fire-once
    assert p.read_bytes() == damaged


@pytest.mark.slow
def test_cli_engine_kill_then_resume_byte_identical(tmp_path):
    """The chaos e2e, through the real CLI: serve with engine-kill@N
    dies by SIGKILL mid-wave; serve --resume finishes the wave; every
    npz matches a clean run byte for byte."""
    reqs = tmp_path / "reqs.jsonl"
    reqs.write_text("".join(
        json.dumps({"id": f"r{i}", "n": c.n, "ntime": c.ntime,
                    "dtype": c.dtype, "bc": c.bc, "bc_value": c.bc_value,
                    "nu": c.nu, "ic": c.ic}) + "\n"
        for i, c in enumerate(WAVE)))
    base = [sys.executable, "-m", "heat_tpu", "serve",
            "--requests", str(reqs), "--lanes", "2", "--chunk", "8",
            "--buckets", "32,48"]
    env = {**os.environ, "JAX_PLATFORMS": "cpu", "JAX_ENABLE_X64": "1",
           "PYTHONPATH": (str(Path(__file__).resolve().parent.parent)
                          + os.pathsep + os.environ.get("PYTHONPATH", ""))}

    rc = subprocess.run(base + ["--out-dir", str(tmp_path / "golden")],
                        env=env, capture_output=True, timeout=300)
    assert rc.returncode == 0, rc.stderr.decode()[-2000:]

    ck = tmp_path / "ck"
    rc = subprocess.run(
        base + ["--out-dir", str(tmp_path / "killed"),
                "--engine-ckpt-interval", "2",
                "--engine-ckpt-dir", str(ck),
                "--inject", "engine-kill@12"],
        env=env, capture_output=True, timeout=300)
    assert rc.returncode == -signal.SIGKILL, (rc.returncode,
                                              rc.stderr.decode()[-2000:])
    mans = sorted(ck.glob("engine_gen*.json"))
    assert mans, "at least one generation must be durable before the kill"
    man = json.loads(mans[-1].read_text())
    # scrub result files the surviving manifest does not vouch for (a
    # kill can leave a half-published npz newer than the manifest; the
    # resume contract only trusts the manifest's done set)
    done = set(man["done"])
    for p in (tmp_path / "killed").glob("*.npz"):
        if p.stem not in done:
            p.unlink()

    rc = subprocess.run(
        base + ["--out-dir", str(tmp_path / "resumed"),
                "--engine-ckpt-interval", "2",
                "--engine-ckpt-dir", str(ck), "--resume", str(ck)],
        env=env, capture_output=True, timeout=300)
    assert rc.returncode == 0, rc.stderr.decode()[-2000:]
    out = rc.stdout.decode()
    assert "serve_resumed" in out

    for i in range(len(WAVE)):
        a = tmp_path / "golden" / f"r{i}.npz"
        b = tmp_path / "killed" / f"r{i}.npz"
        if not b.exists():
            b = tmp_path / "resumed" / f"r{i}.npz"
        assert a.read_bytes() == b.read_bytes(), f"r{i}"


# --- resume-aware front doors ------------------------------------------------


def test_serve_requests_skip_ids(tmp_path):
    """File rows the manifest accounts for are not re-submitted — the
    resume replay is the authority on their state."""
    from heat_tpu.serve import serve_requests

    reqs = tmp_path / "reqs.jsonl"
    reqs.write_text(json.dumps({"id": "a", "n": 16, "ntime": 8}) + "\n"
                    + json.dumps({"id": "b", "n": 16, "ntime": 8}) + "\n")
    records, summary = serve_requests(
        reqs, quiet(lanes=1, buckets=(16,)), skip_ids={"a"})
    assert [r["id"] for r in records] == ["b"]
    assert summary["requests"] == 1


def test_engine_ckpt_interval_validates():
    with pytest.raises(ValueError, match="engine_ckpt_interval"):
        ServeConfig(engine_ckpt_interval=-1)


def test_flight_dump_skipped_without_dirs(tmp_cwd):
    """No flight_dir and no out_dir -> the dump is SKIPPED, never
    written to cwd (the repo root grew 81 stray dumps this way)."""
    eng = Engine(quiet(trace_buffer=64))
    eng._flight_dump("unit-test trigger")
    assert not list(tmp_cwd.glob("flightrec-*.trace.json"))
    assert eng.tracer.dumps == 0
