"""The bench.py capture contract: one parseable JSON line, always.

The reference's whole deliverable is a printed timing line
(fortran/mpi+cuda/heat.F90:291-292); this repo's equivalent is bench.py's
single JSON verdict line. Rounds 1 and 2 each lost it a different way
(rc=1 with nothing parseable; external SIGTERM mid-backoff), so the
contract is now pinned by tests:

* success      -> result line, rc=0
* all-fail     -> error line, rc=1, within the total wall budget
* external kill-> error line BEFORE dying (SIGTERM backstop)
"""

import json
import os
import signal
import subprocess
import sys
import time

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import bench  # noqa: E402


def _last_json_line(out: str) -> dict:
    lines = [l for l in out.strip().splitlines() if l.strip().startswith("{")]
    assert lines, f"no JSON line in output: {out!r}"
    return json.loads(lines[-1])


@pytest.fixture
def restore_signals():
    """supervise() installs SIGTERM/SIGINT/SIGHUP handlers; undo after."""
    saved = {s: signal.getsignal(s)
             for s in (signal.SIGTERM, signal.SIGINT, signal.SIGHUP)}
    yield
    for s, h in saved.items():
        signal.signal(s, h)


def test_success_prints_result_line(monkeypatch, capsys, restore_signals):
    record = {"metric": bench.METRIC, "value": 1.0e11, "unit": "points/s",
              "vs_baseline": 1.1}

    def fake_run(holder, timeout):
        return subprocess.CompletedProcess("worker", 0,
                                           stdout=json.dumps(record),
                                           stderr="")

    monkeypatch.setattr(bench, "_run_worker", fake_run)
    rc = bench.supervise()
    assert rc == 0
    out = _last_json_line(capsys.readouterr().out)
    assert out == record


def test_all_attempts_fail_stays_within_budget(monkeypatch, capsys,
                                               restore_signals):
    """Round 2's bug: per-attempt timeouts but no total budget. Now every
    attempt and backoff is scheduled against one deadline."""
    calls = []

    def fake_run(holder, timeout):
        calls.append(timeout)
        raise subprocess.TimeoutExpired(cmd="worker", timeout=timeout)

    monkeypatch.setattr(bench, "_run_worker", fake_run)
    monkeypatch.setattr(bench, "TOTAL_BUDGET_S", 2)
    monkeypatch.setattr(bench, "ATTEMPT_TIMEOUT_S", 1)
    monkeypatch.setattr(bench, "_MIN_ATTEMPT_S", 0.5)
    monkeypatch.setattr(bench, "BACKOFF_S", (0.1,))
    t0 = time.monotonic()
    rc = bench.supervise()
    elapsed = time.monotonic() - t0
    assert rc == 1
    assert elapsed < 10  # exited on its own, well inside any kill window
    out = _last_json_line(capsys.readouterr().out)
    assert out["metric"] == bench.METRIC
    assert out["value"] == 0.0
    assert "error" in out
    # per-attempt timeout is clamped to the remaining budget
    assert all(t <= bench.ATTEMPT_TIMEOUT_S for t in calls)


def test_budget_exhaustion_is_reported(monkeypatch, capsys, restore_signals):
    def fake_run(holder, timeout):
        time.sleep(timeout)
        raise subprocess.TimeoutExpired(cmd="worker", timeout=timeout)

    monkeypatch.setattr(bench, "_run_worker", fake_run)
    monkeypatch.setattr(bench, "TOTAL_BUDGET_S", 1)
    monkeypatch.setattr(bench, "ATTEMPT_TIMEOUT_S", 0.6)
    monkeypatch.setattr(bench, "_MIN_ATTEMPT_S", 0.5)
    rc = bench.supervise()
    assert rc == 1
    out = _last_json_line(capsys.readouterr().out)
    assert "budget exhausted" in out["error"]


def test_sigterm_leaves_parseable_line(tmp_path):
    """The round-2 killer, reproduced: an external deadline SIGTERMs the
    supervisor mid-attempt. The backstop handler must print the verdict
    line before the process dies."""
    env = dict(os.environ)
    env.update(HEAT_BENCH_TIMEOUT_S="300", HEAT_BENCH_TOTAL_BUDGET_S="300",
               JAX_PLATFORMS="cpu")
    proc = subprocess.Popen(
        [sys.executable, os.path.join(os.path.dirname(bench.__file__),
                                      "bench.py")],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True,
        env=env)

    def our_worker_pids():
        # scoped to THIS supervisor's children so a concurrent bench run
        # (e.g. the chip sweep) can't satisfy the readiness check
        r = subprocess.run(
            ["pgrep", "-P", str(proc.pid), "-f", "bench.py --worker"],
            capture_output=True, text=True)
        return {int(p) for p in r.stdout.split()}

    # Wait for the supervisor's worker to appear before signalling: the
    # worker is spawned only after the signal backstop is installed, so
    # its existence proves the handler is live. (A fixed 2 s sleep raced
    # interpreter startup under load — SIGTERM landed before the handler
    # and the default action killed the process with rc=-15.)
    deadline = time.monotonic() + 60
    while time.monotonic() < deadline:
        ours = our_worker_pids()
        if ours:
            break
        if proc.poll() is not None:
            out, _ = proc.communicate()
            raise AssertionError(
                f"supervisor exited early rc={proc.returncode}; "
                f"stdout: {out!r}")
        time.sleep(0.1)
    else:
        proc.kill()
        raise AssertionError("worker never appeared within 60s")
    proc.send_signal(signal.SIGTERM)
    out, _ = proc.communicate(timeout=30)
    assert proc.returncode == 1
    parsed = _last_json_line(out)
    assert parsed["metric"] == bench.METRIC
    assert "signal 15" in parsed["error"]
    # the backstop must also reap the in-flight worker — an orphan would
    # keep holding the (single) chip for up to ATTEMPT_TIMEOUT_S. The
    # worker pids were recorded while the supervisor was alive (pgrep -P
    # can't find them post-reparenting), so check them directly.
    time.sleep(1.0)
    leaked = set()
    for pid in ours:
        try:
            with open(f"/proc/{pid}/cmdline", "rb") as f:
                cmdline = f.read()  # empty for zombies — dead, awaiting reap
            with open(f"/proc/{pid}/stat") as f:
                state = f.read().rsplit(")", 1)[1].split()[0]
        except OSError:
            continue  # gone entirely
        # identity check guards against PID reuse during the settle sleep
        if state != "Z" and b"--worker" in cmdline:
            leaked.add((pid, state))
    assert not leaked, f"orphaned worker pids: {leaked}"


def test_worker_constants_match_library():
    """ADVICE r2: STEPS/REPEATS drift between bench.py and
    heat_tpu.benchmark would silently change the measurement recorded
    under the same metric string."""
    from heat_tpu import benchmark

    assert bench.N == benchmark.N
    assert bench.STEPS == benchmark.STEPS
    assert bench.REPEATS == benchmark.REPEATS
    assert bench.METRIC == benchmark.metric_name(bench.N)


def test_error_line_carries_last_good(tmp_path, monkeypatch):
    """Outage-era error lines attach the cached last real measurement
    under last_good (timestamped) — never as the headline value."""
    cache = tmp_path / "last_bench.json"
    cache.write_text(json.dumps({"metric": bench.METRIC, "value": 1.5e11,
                                 "measured_ts": 1785469590.0}))
    monkeypatch.setattr(bench, "_LAST_GOOD", str(cache))
    rec = json.loads(bench._error_line("backend gone"))
    assert rec["value"] == 0.0 and rec["error"] == "backend gone"
    assert rec["last_good"]["value"] == 1.5e11

    monkeypatch.setattr(bench, "_LAST_GOOD", str(tmp_path / "missing.json"))
    rec2 = json.loads(bench._error_line("backend gone"))
    assert "last_good" not in rec2  # absent cache: plain error line


def test_error_line_rejects_mismatched_last_good(tmp_path, monkeypatch):
    """A stale cache from a DIFFERENT benchmark configuration (other
    N/STEPS -> other metric string) must not ride along on this metric's
    error line (advisor r3 finding)."""
    cache = tmp_path / "last_bench.json"
    cache.write_text(json.dumps({
        "metric": "grid_points_per_sec_per_chip_1024x1024_f32_pallas",
        "value": 9.9e10, "measured_ts": 1785469590.0}))
    monkeypatch.setattr(bench, "_LAST_GOOD", str(cache))
    rec = json.loads(bench._error_line("backend gone"))
    assert rec["value"] == 0.0
    assert "last_good" not in rec
