"""Semantic scheduling (ISSUE 16): ``until=steady`` early exit, the
eigenmode ETA predictor, and convergence-aware dispatch.

The load-bearing contracts:

- an ``until=steady`` request retires at the first chunk boundary whose
  residual EWMA passes tolerance, and its record is BIT-IDENTICAL to a
  fixed-step run truncated at that boundary — at dispatch depths 0 and
  2, packed (xla + pallas) and mega placements. The exit is a
  *scheduling* decision: the device program never changes;
- the predictor (runtime/convergence.py) fuses the closed-form
  eigenmode decay rate with the observed residual slope, and its
  admission-time ETA shapes EDF order and fair-share billing without
  perturbing any pre-existing ordering;
- ``until``/``tol`` validate loudly everywhere (config, JSONL/HTTP
  front doors, Engine.submit, CLI);
- savings are accounted end to end: per-record usage, the tenant
  ledger, Engine.summary(), /metrics, /statusz, and the trace all
  reconcile.
"""

import json
import math
import time

import numpy as np
import pytest

from heat_tpu.backends import solve
from heat_tpu.config import HeatConfig, validate_until_fields
from heat_tpu import grid
from heat_tpu.runtime import convergence, faults
from heat_tpu.runtime import trace as trace_mod
from heat_tpu.runtime.prof import UsageLedger
from heat_tpu.serve import Engine, ServeConfig
from heat_tpu.serve import api as api_mod
from heat_tpu.serve import policy as policy_mod
from heat_tpu.serve import scheduler as sched_mod
from heat_tpu.serve.gateway import render_metrics, render_statusz


@pytest.fixture(autouse=True)
def _fresh_faults():
    faults.reset()
    yield
    faults.reset()


def quiet(**kw) -> ServeConfig:
    kw.setdefault("emit_records", False)
    return ServeConfig(**kw)


# sine is THE eigenmode IC: its residual decays exactly as lambda**s
# (grid.sine_decay_factor), so tol=2e-3 on the n=12 grid crosses near
# step ~80 — far inside the requested 160 steps
STEADY_CFG = HeatConfig(n=12, ntime=160, dtype="float64", bc="edges",
                        ic="sine")
STEADY_TOL = 2e-3
CO_CFG = HeatConfig(n=12, ntime=40, dtype="float64", bc="edges", ic="hat")


# --- packed bit-identity ------------------------------------------------------


@pytest.mark.parametrize("depth", [0, 2])
def test_steady_bit_identical_to_truncated_fixed_step(tmp_path, depth):
    """Acceptance: the early-exit record equals the fixed-step run of
    ``ntime=steps_done`` bit for bit (in-memory field AND npz payload),
    while a fixed-step co-lane stays untouched — at depths 0 and 2."""
    out = tmp_path / f"steady{depth}"
    eng = Engine(quiet(lanes=2, chunk=8, buckets=(16,),
                       dispatch_depth=depth, out_dir=str(out),
                       keep_fields=True))
    sid = eng.submit(STEADY_CFG, until="steady", tol=STEADY_TOL)
    cid = eng.submit(CO_CFG)
    recs = {r["id"]: r for r in eng.results()}

    rec = recs[sid]
    assert rec["status"] == "ok"
    assert rec["until"] == "steady" and rec["exit"] == "steady"
    assert 0 < rec["steps_done"] < STEADY_CFG.ntime
    trunc = STEADY_CFG.with_(ntime=int(rec["steps_done"]))
    solo = solve(trunc).T
    np.testing.assert_array_equal(rec["T"], solo)
    with np.load(out / f"{sid}.npz") as z:
        assert z["T"].tobytes() == solo.tobytes()
        assert int(z["step"]) == rec["steps_done"]

    co = recs[cid]
    assert co["exit"] == "steps" and co["steps_done"] == CO_CFG.ntime
    np.testing.assert_array_equal(co["T"], solve(CO_CFG).T)

    s = eng.summary()
    assert s["steady_exits"] == 1
    assert s["steps_saved"] == STEADY_CFG.ntime - rec["steps_done"]
    assert rec["usage"]["steps_saved"] == s["steps_saved"]
    assert co["usage"]["steps_saved"] == 0
    # the admission-time eigenmode ETA brackets the actual retirement
    pred = rec["predicted_steps"]
    assert pred is not None and 0 < pred <= STEADY_CFG.ntime
    assert 0.5 <= pred / rec["steps_done"] <= 1.5


def test_steady_unreachable_tol_runs_all_steps():
    """A tolerance the decay never reaches inside ntime falls back to
    the fixed-step semantics: exit='steps', zero savings, bit-identical
    to the plain run (ntime is the hard cap, never exceeded)."""
    cfg = STEADY_CFG.with_(ntime=48)
    eng = Engine(quiet(lanes=1, chunk=8, buckets=(16,)))
    rid = eng.submit(cfg, until="steady", tol=1e-14)
    recs = {r["id"]: r for r in eng.results()}
    rec = recs[rid]
    assert rec["status"] == "ok"
    assert rec["exit"] == "steps" and rec["steps_done"] == cfg.ntime
    assert rec["usage"]["steps_saved"] == 0
    np.testing.assert_array_equal(rec["T"], solve(cfg).T)
    assert eng.summary()["steady_exits"] == 0


def test_steady_pallas_lane_kernel_matches_xla():
    """The steady decision rides the boundary vector, not the chunk
    body: pallas and xla lane kernels retire at the same boundary with
    byte-identical fields (f32 — the lane-kernel dtype)."""
    cfg = STEADY_CFG.with_(dtype="float32")
    outs = {}
    for kernel in ("xla", "pallas"):
        eng = Engine(quiet(lanes=2, chunk=4, buckets=(12,),
                           lane_kernel=kernel))
        rid = eng.submit(cfg, until="steady", tol=STEADY_TOL)
        recs = {r["id"]: r for r in eng.results()}
        assert eng.lane_kernel_fallbacks == 0
        assert recs[rid]["exit"] == "steady"
        outs[kernel] = recs[rid]
    assert outs["xla"]["steps_done"] == outs["pallas"]["steps_done"]
    assert (np.asarray(outs["xla"]["T"]).tobytes()
            == np.asarray(outs["pallas"]["T"]).tobytes())
    trunc = cfg.with_(ntime=int(outs["xla"]["steps_done"]))
    np.testing.assert_array_equal(outs["xla"]["T"], solve(trunc).T)


# --- mega bit-identity --------------------------------------------------------


@pytest.mark.parametrize("depth", [0, 2])
def test_steady_mega_lane_bit_identical(depth):
    """The mega tier honors until=steady too: the mesh-spanning lane
    retires at its frontier and matches the solo sharded drive of the
    truncated config (8-virtual-device harness, tests/conftest.py)."""
    cfg = HeatConfig(n=16, ntime=120, dtype="float64", bc="edges",
                     ic="sine")
    eng = Engine(quiet(lanes=2, chunk=8, buckets=(8,),
                       dispatch_depth=depth, keep_fields=True))
    rid = eng.submit(cfg, until="steady", tol=5e-3)
    recs = {r["id"]: r for r in eng.results()}
    rec = recs[rid]
    assert rec["status"] == "ok" and rec["placement"] == "mega"
    assert rec["exit"] == "steady"
    assert 0 < rec["steps_done"] < cfg.ntime
    trunc = cfg.with_(ntime=int(rec["steps_done"]))
    np.testing.assert_array_equal(
        rec["T"], solve(trunc.with_(backend="sharded")).T)
    s = eng.summary()
    assert s["steady_exits"] == 1
    assert s["steps_saved"] == cfg.ntime - rec["steps_done"]


# --- predictor (runtime/convergence.py) ---------------------------------------


def test_closed_form_rate_matches_sine_decay_factor():
    cfg = HeatConfig(n=24, ntime=10, dtype="float64", ic="sine")
    lam = grid.sine_decay_factor(cfg)
    assert 0.0 < lam < 1.0
    assert convergence.closed_form_log_rate(cfg) == pytest.approx(
        math.log(lam))


def test_rate_fuser_recovers_exact_geometric_decay():
    """Fed an exact lambda**s residual sequence (variable chunk sizes —
    the observed-step delta comes from `remaining`, not an assumed
    chunk), the fused rate converges to log(lambda) and the prediction
    lands within one step of the analytic crossing."""
    lam = 0.99
    fuser = convergence.RateFuser(None)   # no closed form: observed only
    resid, remaining = 1e-2, 400
    for k in (8, 8, 4, 8, 12, 8):         # tail/variable chunks
        fuser.observe(resid, remaining)
        remaining -= k
        resid *= lam ** k
    assert fuser.fused_log_rate() == pytest.approx(math.log(lam),
                                                   rel=1e-9)
    tol = 1e-4
    pred = convergence.predict_steps_to_tol(resid, tol,
                                            fuser.fused_log_rate())
    exact = math.ceil(math.log(tol / resid) / math.log(lam))
    assert abs(pred - exact) <= 1


def test_predict_steps_to_tol_edge_cases():
    log_rate = math.log(0.99)
    assert convergence.predict_steps_to_tol(1e-5, 1e-4, log_rate) == 0
    assert convergence.predict_steps_to_tol(1e-2, 1e-4, None) is None
    # a non-decaying (growing) rate can never promise steady
    assert convergence.predict_steps_to_tol(1e-2, 1e-4,
                                            math.log(1.5)) is None


def test_admission_prediction_clamped_to_request():
    """The admission ETA is capped by ntime (never promises more work
    than requested) and floors at 0."""
    pred = convergence.predict_admission_steps(STEADY_CFG, STEADY_TOL)
    assert pred is not None and 0 < pred <= STEADY_CFG.ntime
    tiny = convergence.predict_admission_steps(
        STEADY_CFG.with_(ntime=8), STEADY_TOL)
    assert tiny == 8


# --- convergence-aware admission ordering -------------------------------------


def _req(seq, ntime=100, until="steps", predicted=None,
         slo_class="standard", deadline_t=None):
    return sched_mod.Request(
        id=f"r{seq}", cfg=HeatConfig(n=12, ntime=ntime, dtype="float32"),
        submit_t=0.0, seq=seq, slo_class=slo_class, deadline_t=deadline_t,
        until=until, predicted_steps=predicted)


def test_edf_orders_on_predicted_finish():
    """Among undated same-class peers, EDF runs the steady request with
    the earliest PREDICTED finish first; deadlines and classes still
    dominate, and unpredicted requests keep exact FIFO order (the
    pre-ISSUE-16 orderings are preserved bit for bit)."""
    q = policy_mod.EdfQueue()
    reqs = [
        _req(0),                                        # fixed, undated
        _req(1, until="steady", predicted=40),
        _req(2, until="steady", predicted=10),
        _req(3, deadline_t=5.0),                        # dated: first
        _req(4, until="steady", predicted=None),        # cold predictor
        _req(5, slo_class="interactive"),               # class trumps all
    ]
    for r in reqs:
        q.push(r)
    order = [q.pop().id for _ in range(len(reqs))]
    assert order == ["r5", "r3", "r2", "r1", "r0", "r4"]


def test_edf_degrades_to_fifo_without_predictions():
    q = policy_mod.EdfQueue()
    for r in (_req(0), _req(1), _req(2)):
        q.push(r)
    assert [q.pop().id for _ in range(3)] == ["r0", "r1", "r2"]


def test_fair_share_bills_predicted_work():
    """Fair share charges an until=steady tenant its PREDICTED steps —
    a tenant of early-exiting requests gets proportionally more
    admissions than one of equal-nominal fixed-step requests."""
    q = policy_mod.FairShareQueue()
    s1, s2 = (_req(0, until="steady", predicted=10),
              _req(1, until="steady", predicted=10))
    f1, f2 = _req(2), _req(3)
    for r in (s1, s2):
        r.tenant = "steady-co"
    for r in (f1, f2):
        r.tenant = "fixed-co"
    for r in (s1, s2, f1, f2):
        q.push(r)
    # vtime tie -> tenant name; then the cheap (predicted 10 of 100)
    # steady tenant stays below the fixed tenant's virtual time
    order = [q.pop().id for _ in range(4)]
    assert order == ["r2", "r0", "r1", "r3"]


# --- until/tol validation -----------------------------------------------------


def test_validate_until_fields_contract():
    assert validate_until_fields(None, None) == ("steps", None)
    assert validate_until_fields("steps", None) == ("steps", None)
    assert validate_until_fields("steady", None) == ("steady", None)
    assert validate_until_fields("steady", 1e-3) == ("steady", 1e-3)
    assert validate_until_fields("steady", "1e-3") == ("steady", 1e-3)
    with pytest.raises(ValueError, match="until"):
        validate_until_fields("forever", None)
    # the loud-typo contract: tol without steady is a rejection
    with pytest.raises(ValueError, match="tol"):
        validate_until_fields(None, 1e-3)
    with pytest.raises(ValueError, match="tol"):
        validate_until_fields("steps", 1e-3)
    for bad in (0.0, -1.0, float("nan"), float("inf"), "tight"):
        with pytest.raises(ValueError, match="tol"):
            validate_until_fields("steady", bad)


def test_parse_request_obj_until_rows():
    row = api_mod.parse_request_obj(
        {"n": 12, "ntime": 8, "until": "steady", "tol": 1e-3})
    assert row.error is None
    assert row.until == "steady" and row.tol == 1e-3
    row = api_mod.parse_request_obj({"n": 12, "ntime": 8})
    assert row.error is None and row.until == "steps" and row.tol is None
    # malformed until/tol is that request's rejection, not a raise
    for bad in ({"until": "forever"}, {"tol": 1e-3},
                {"until": "steady", "tol": -1.0}):
        row = api_mod.parse_request_obj({"n": 12, "ntime": 8, **bad})
        assert row.error is not None and row.cfg is None


def test_engine_submit_validates_until():
    eng = Engine(quiet(lanes=1, chunk=8, buckets=(16,)))
    with pytest.raises(ValueError, match="until"):
        eng.submit(CO_CFG, until="forever")
    with pytest.raises(ValueError, match="tol"):
        eng.submit(CO_CFG, tol=1e-3)
    assert eng.results() == []


def test_serve_config_default_steady_tol_applies(tmp_path):
    """An until=steady request without its own tol uses the engine-wide
    --steady-tol default."""
    eng = Engine(quiet(lanes=1, chunk=8, buckets=(16,),
                       steady_tol=STEADY_TOL))
    rid = eng.submit(STEADY_CFG, until="steady")
    recs = {r["id"]: r for r in eng.results()}
    assert recs[rid]["exit"] == "steady"
    assert recs[rid]["steps_done"] < STEADY_CFG.ntime


# --- accounting reconciliation (ledger + /metrics + /statusz) -----------------


def test_steps_saved_reconciles_across_surfaces():
    eng = Engine(quiet(lanes=2, chunk=8, buckets=(16,)))
    sid = eng.submit(STEADY_CFG, until="steady", tol=STEADY_TOL,
                     tenant="acme")
    eng.submit(CO_CFG, tenant="acme")
    recs = eng.results()
    s = eng.summary()
    saved = sum(r["usage"]["steps_saved"] for r in recs)
    assert saved == s["steps_saved"] > 0
    by_id = {r["id"]: r for r in recs}
    assert (by_id[sid]["usage"]["steps"] + by_id[sid]["usage"]["steps_saved"]
            == STEADY_CFG.ntime)

    # the engine's live ledger and an offline re-aggregation of the
    # records agree (the heat-tpu usage reconciliation contract)
    live = eng.prof.ledger.snapshot()
    assert live["totals"]["steps_saved"] == saved
    offline = UsageLedger()
    for r in recs:
        offline.add(r["tenant"], r["class"], r["status"], r["usage"],
                    placement=r.get("placement"))
    assert offline.snapshot()["totals"]["steps_saved"] == saved
    assert (offline.snapshot()["tenants"]["acme"]["steps_saved"]
            == saved)

    # /metrics and /statusz surface the same totals
    metrics = render_metrics(eng)
    assert "heat_tpu_serve_steady_exits_total 1" in metrics
    assert f"heat_tpu_serve_steps_saved_total {saved}" in metrics
    assert "heat_tpu_usage_steps_saved_total" in metrics
    assert "heat_tpu_numerics_predicted_eta_steps" in metrics
    statusz = render_statusz(eng)
    assert f"semantic scheduling: 1 steady exit(s), {saved} step(s) saved" \
        in statusz


def test_predicted_eta_gauge_live_on_metrics():
    """While a steady lane is mid-flight, /metrics exports its per-lane
    predicted-ETA gauge (labeled by request id)."""
    eng = Engine(quiet(lanes=1, chunk=8, buckets=(16,)))
    eng.start()
    try:
        # unreachable tol: the lane stays resident for the whole drain,
        # so the gauge cannot race its own retirement
        rid = eng.submit(STEADY_CFG.with_(ntime=4000), until="steady",
                         tol=1e-30)
        # wait for the observatory to have enough boundaries for an ETA
        # (cheap probe), then scrape the expensive /metrics render once
        seen = False
        for _ in range(400):
            if eng.numerics.eta_steps(rid) is not None:
                seen = True
                break
            time.sleep(0.02)
        assert seen, "predictor never produced an ETA"
        metrics = render_metrics(eng)
        assert (f'heat_tpu_numerics_predicted_eta_steps{{id="{rid}"}}'
                in metrics)
    finally:
        eng.shutdown()


# --- gateway e2e --------------------------------------------------------------


def test_gateway_steady_request_e2e():
    """A steady request over real HTTP: the JSONL line POSTed to
    /v1/solve retires early, its record carries the semantic-scheduling
    fields, and a malformed until is a per-line rejection."""
    import urllib.error
    import urllib.request

    from heat_tpu.serve.gateway import Gateway

    timeout = 60
    eng = Engine(quiet(lanes=2, chunk=8, buckets=(16,)))
    gw = Gateway(eng, "127.0.0.1", 0).start()
    try:
        def post(body):
            req = urllib.request.Request(
                f"http://{gw.address}/v1/solve?wait=0",
                data=body.encode(), method="POST")
            try:
                resp = urllib.request.urlopen(req, timeout=timeout)
                raw, st = resp.read(), resp.status
            except urllib.error.HTTPError as e:
                raw, st = e.read(), e.code
            return st, [json.loads(l) for l in raw.decode().splitlines()
                        if l.strip()]

        st, lines = post(json.dumps(
            {"id": "s", "n": 12, "ntime": 160, "ic": "sine",
             "until": "steady", "tol": STEADY_TOL}) + "\n")
        assert st == 202 and lines[0]["accepted"] == ["s"]
        rec = eng.wait("s", timeout=timeout)
        assert rec["status"] == "ok" and rec["exit"] == "steady"
        assert rec["steps_done"] < 160
        assert rec["predicted_steps"] is not None

        # typo'd until: that line is rejected, nothing is admitted
        st, lines = post(json.dumps(
            {"id": "bad", "n": 12, "ntime": 8, "until": "forever"}) + "\n")
        assert lines[0]["accepted"] == []
        (row,) = lines[0]["records"]
        assert row["status"] == "rejected" and "until" in row["error"]
    finally:
        eng.shutdown(timeout=timeout)
        gw.close()


# --- trace (heat-tpu trace) ---------------------------------------------------


def test_trace_carries_steady_exit_instant(tmp_path):
    path = tmp_path / "steady.trace.json"
    eng = Engine(quiet(lanes=2, chunk=8, buckets=(16,),
                       trace=str(path)))
    sid = eng.submit(STEADY_CFG, until="steady", tol=STEADY_TOL)
    recs = {r["id"]: r for r in eng.results()}
    assert recs[sid]["exit"] == "steady"
    evs = json.loads(path.read_text())["traceEvents"]
    (ev,) = [e for e in evs if e.get("name") == "steady-exit"]
    args = ev["args"]
    assert args["id"] == sid
    assert args["at_step"] == recs[sid]["steps_done"]
    assert args["requested"] == STEADY_CFG.ntime
    assert args["saved"] == STEADY_CFG.ntime - recs[sid]["steps_done"]
    # predicted-vs-actual retirement boundary rides the same instant
    assert args["predicted_at_step"] == recs[sid]["predicted_steps"]
    # and the text summary counts it among the notable instants
    text = "\n".join(trace_mod.summarize_file(path))
    assert "steady-exit" in text


# --- CLI ----------------------------------------------------------------------


def test_serve_cli_steady_requests(tmp_cwd, capsys):
    """`heat-tpu serve --requests` honors until/tol lines end to end:
    the record carries exit=steady and the report prints the semantic-
    scheduling savings line; --steady-tol parses as the default."""
    from heat_tpu.cli import main

    (tmp_cwd / "reqs.jsonl").write_text(
        '{"id": "s", "n": 12, "ntime": 160, "ic": "sine",'
        ' "until": "steady", "tol": 2e-3}\n'
        '{"id": "f", "n": 12, "ntime": 40}\n')
    assert main(["serve", "--requests", "reqs.jsonl", "--buckets", "16",
                 "--chunk", "8", "--steady-tol", "1e-9"]) == 0
    out = capsys.readouterr().out
    assert '"exit": "steady"' in out
    assert "semantic scheduling: 1 steady exit(s)," in out
    recs = [json.loads(l) for l in out.splitlines()
            if l.startswith("{") and '"event": "serve_request"' in l]
    by_id = {r["id"]: r for r in recs}
    assert by_id["s"]["exit"] == "steady"
    assert by_id["s"]["steps_done"] < 160
    assert by_id["s"]["predicted_steps"] is not None
    assert by_id["f"]["exit"] == "steps" and by_id["f"]["steps_done"] == 40
